"""Database session facade — the tcop/postgres.c + psql surface.

One object owns the catalog, storage, mesh, settings, and executor; .sql()
is exec_simple_query (reference: src/backend/tcop/postgres.c:1622): parse ->
bind -> parallelize -> compile -> dispatch -> gather. DDL/DML/utility
statements route to their handlers, mirroring ProcessUtility.
"""

from __future__ import annotations

import copy as _copy
import csv as _csv
import dataclasses as _dc
import glob as _glob
import hashlib
import io
import json as _json
import os
import shutil
import subprocess
import sys as _sys
import tempfile
import threading
import time
import uuid as _uuid
import warnings
from collections import OrderedDict as _OD
from contextlib import ExitStack, contextmanager as _contextmanager
from types import SimpleNamespace

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.analysis.plancheck import validate_plan
from greengage_tpu.catalog import (Catalog, Column, DistPolicy, Partition,
                                   PolicyKind, TableSchema)
from greengage_tpu.config import Settings
from greengage_tpu.exec.executor import (Executor, OutOfDeviceMemory,
                                         QueryError, Result)
from greengage_tpu.parallel import make_mesh
from greengage_tpu.planner import plan_query
from greengage_tpu.planner.logical import describe
from greengage_tpu.runtime import memaccount as _memaccount
from greengage_tpu.runtime import overload as _overload
from greengage_tpu.runtime import trace as _trace
from greengage_tpu.runtime.interrupt import (REGISTRY as _INTERRUPTS,
                                             StatementCancelled,
                                             check_interrupts)
from greengage_tpu.runtime.logger import counters as _counters
from greengage_tpu.runtime.logger import histograms as _histograms
from greengage_tpu.runtime.trace import TRACES as _TRACES
from greengage_tpu.sql import ast as A
from greengage_tpu.sql.binder import (Binder, _contains_agg,
                                       type_from_name)
from greengage_tpu.sql.parser import SqlError, parse
from greengage_tpu.storage import TableStore


class Database:
    def __init__(self, path: str | None = None, numsegments: int | None = None,
                 devices=None, mirrors: bool = False, multihost=None):
        import jax

        self.multihost = multihost   # parallel.multihost.MultihostRuntime
        devs = list(devices) if devices is not None else jax.devices()
        self._devices = devs
        if path is not None and os.path.exists(os.path.join(path, "catalog.json")):
            self.catalog = Catalog.load(path)
            if numsegments is None:
                numsegments = self.catalog.segments.numsegments
            elif self.catalog.segments.numsegments != numsegments:
                raise ValueError(
                    f"cluster width mismatch: on-disk {self.catalog.segments.numsegments}, "
                    f"requested {numsegments} (run gpexpand-style redistribution)")
        else:
            if numsegments is None:
                numsegments = len(devs)
            self.catalog = Catalog(numsegments, path=path, mirrors=mirrors)
        self.numsegments = numsegments
        if path is None:
            path = tempfile.mkdtemp(prefix="ggtpu_")
            self.catalog.path = path
        self.path = path
        is_worker = multihost is not None and not multihost.is_coordinator
        if not is_worker:
            self.catalog._save()   # persist width even before the first table
        self.store = TableStore(path, self.catalog)
        if not is_worker:
            # workers never write: recovery/reconciliation would race the
            # coordinator's in-flight transactions
            self.store.manifest.recover()   # in-doubt resolution on startup
            self.store.reconcile_widths()   # expansion crash recovery
        self.settings = Settings()
        # persisted cluster GUCs (the gpconfig role): settings.json holds
        # operator-set values every process (coordinator AND workers)
        # adopts at connect — the per-segment-config-file parity without
        # per-segment files, since settings steer lockstep mesh decisions
        # and must be identical everywhere anyway
        sp = os.path.join(path, "settings.json")
        # adoption failures are COLLECTED, never swallowed (guc.c rejects
        # bad values at SET; the deferred analog is a visible warning):
        # `gg state` prints these, so an operator typo in `gg config`
        # can't become silent divergence between set and running values
        self.settings_warnings: list[str] = []
        if os.path.exists(sp):
            try:
                with open(sp) as f:
                    for k, v in _json.load(f).items():
                        try:
                            self.settings.set(k, v)
                        except ValueError as e:
                            self.settings_warnings.append(
                                f"persisted setting {k!r}={v!r} not adopted: {e}")
            except (OSError, ValueError) as e:
                self.settings_warnings.append(f"settings.json unreadable: {e}")
        self._mh_degraded: str | None = None
        # measured cost-model primitives, if `gg checkperf --device
        # --apply` ran against this cluster (planner/cost.set_calibration;
        # workers load the same file, keeping plan choices in lockstep)
        cal_path = os.path.join(path, "calibration.json")
        from greengage_tpu.planner import cost as _cost

        cal = None
        if os.path.exists(cal_path):
            try:
                with open(cal_path) as f:
                    cal = _json.load(f)
            except (OSError, ValueError):
                cal = None
        # always (re)install — an uncalibrated cluster opened after a
        # calibrated one in the same process must get the defaults back
        _cost.set_calibration(cal)
        # feedback-driven cost calibration (planner/feedback.py): the
        # per-plan-digest store of observed actuals vs estimates,
        # persisted beside the catalog (and shipped by the standby meta
        # sync). Workers read the shared file but never write it — only
        # the coordinator persists, and workers adopt the coordinator's
        # applied scales from each statement broadcast instead of
        # reconciling locally (lockstep planning, parallel/multihost.py)
        from greengage_tpu.planner.feedback import FeedbackStore

        self.feedback = FeedbackStore(os.path.join(path, "feedback.json"),
                                      persist=not is_worker,
                                      settings=self.settings)
        # planner overlap credit for pipelined motion (same process-global
        # pattern; recomputed on SET motion_pipeline*)
        _cost.set_motion_overlap(self._motion_overlap_factor())
        # the store's read-path self-heal honors storage_autorepair live,
        # and the block-cache registry reads scan_cache_limit_mb live
        self.store.settings = self.settings
        self.store.blockcache.settings = self.settings
        # persistent XLA compilation cache (docs/PERF.md warm-cache note):
        # wired HERE from the xla_cache_dir GUC instead of relying on the
        # ambient environment; an explicit GGTPU_XLA_CACHE env (incl. "0"
        # = off) still wins for operators who set it
        self._apply_xla_cache_dir()
        # bound-plan LRU (plancache.c analog): (statement signature,
        # manifest version) -> (planned, consts, outs, exec_key, param
        # types). Literal-parameterized keys via sql/paramize.py; bounded
        # by the plan_cache_size GUC (_cached_plan)

        self._select_cache: dict = _OD()
        # per-thread (threaded SQL server): see the _plan_cache_info property
        self._pc_info_local = threading.local()
        # statement signatures the binder proved unparameterizable: later
        # literal variants of the shape skip the doomed normalized bind
        # and go straight to the value-pinned plan (bounded backstop)
        self._paramize_fallback: set = set()
        self.mesh = make_mesh(numsegments, devs)
        self.executor = Executor(self.catalog, self.store, self.mesh,
                                 numsegments, self.settings,
                                 multihost=multihost)
        # measured admission: the executor prefers the store's measured
        # per-shape footprint and persisted capacity hints once a shape
        # is warm (exec/executor.py _admission_bytes / run)
        self.executor.feedback = self.feedback
        if not is_worker:
            # spill segments whose owning process died mid-pass (tiered
            # workfile; live paths clean up in their own finally)
            from greengage_tpu.exec import workfile as _workfile
            _workfile.sweep_orphans(
                _workfile.spill_dir_of(self.settings, self.store))
        # vectorized serving pipeline (exec/batchserve.py): created
        # lazily on the first batch-eligible statement so the two
        # pipeline threads only exist when batch_serving_enabled is on
        self._batch_server = None
        self._batch_server_mu = threading.Lock()
        # last brownout state this Database observed (runtime/overload.py
        # is process-wide; the edge effects — prompt cache eviction, the
        # log line — are per-Database and applied by _overload_tick)
        self._overload_seen = False
        from greengage_tpu.runtime.dtm import DtmSession
        from greengage_tpu.runtime.fts import FtsProber
        from greengage_tpu.runtime.replication import Replicator

        from greengage_tpu.runtime.resqueue import ResourceQueue

        # transaction state is PER THREAD: the SQL server runs one thread
        # per connection, so each wire connection (and each direct-API
        # thread) gets its own transaction, like one backend per libpq
        # connection (reference: src/backend/cdb/cdbtm.c MyTmGxact being
        # per-backend state)
        self._DtmSession = DtmSession
        self._dtm_local = None   # created below once threading is imported
        self.resqueue = ResourceQueue(self.settings)
        from greengage_tpu.runtime.resgroup import (ResourceGroup,
                                                    ResourceGroupManager)

        self.resgroups = ResourceGroupManager(
            self.settings,
            {d["name"]: ResourceGroup.from_dict(d)
             for d in self.catalog.resource_groups})
        self.replicator = (Replicator(self.store, self.catalog.segments)
                           if self.catalog.segments.has_mirrors() else None)
        self.fts = FtsProber(self.catalog.segments, self.mesh, store=self.store,
                             on_change=self.catalog._save)
        if not is_worker:
            # topology gauge (asserted by the reform tests; `gg ps` shows it)
            _counters.set("mh_topology_version", self.catalog.segments.version)
            # coordinator liveness beat (runtime/standby.py): stamp at
            # init so a registered standby's watcher sees this primary
            # alive before its first commit; the post-commit hook and the
            # FTS prober cadence keep it fresh thereafter
            from greengage_tpu.runtime import standby as _standby

            if _standby.registered_standby(self.path) is not None:
                _standby.primary_beat(self.path,
                                      self.catalog.segments.version)
                # the probe cadence re-stamps the beat while idle, so an
                # idle-but-alive primary never looks dead to the watcher
                self.fts.start()
        from greengage_tpu.runtime.logger import ClusterLog

        # elog/syslogger analog: CSV logs under <cluster>/log (mined by
        # `gg logfilter`); workers stay quiet (the coordinator logs)
        self.log = ClusterLog(self.path, enabled=not is_worker)
        self.store.log = self.log   # repair/quarantine events land in the log
        self.log.info("lifecycle", f"database ready: {numsegments} segments, "
                      f"{len(devs)} devices")
        for w in self.settings_warnings:
            self.log.log("WARNING", "settings", w)
        self.stat_activity: list[dict] = []   # recent-query ring (gpperfmon analog)
        self._cursors: dict[str, object] = {}  # parallel retrieve cursors
        self._cursor_owner: dict[str, int] = {}  # cursor -> thread ident
        # monotonic DROP TABLE log: an in-flight (unlocked) DECLARE
        # compares its pre-run mark against this at registration to catch
        # a table dropped out from under it mid-run. _drop_base counts
        # pruned entries (the log is cleared whenever no DECLARE is in
        # flight, so it cannot grow with long-lived drop-heavy sessions)
        self._drop_log: list[str] = []
        self._drop_base = 0
        self._inflight_declares = 0
        self._load_extensions()
        # serializes write/DDL statements across threads sharing this
        # Database (server connections); readers stay lock-free on
        # manifest snapshots. Autocommit single-table appends take the
        # SHARED mode plus a per-table lock, so appenders to different
        # tables run concurrently end-to-end (per-table delta manifests
        # make their commits contention-free too — docs/ROBUSTNESS.md)

        self._write_lock = _RWLock()
        self._table_locks: dict[str, threading.RLock] = {}
        self._table_locks_mu = threading.Lock()
        # post-commit replication/archive is not reentrancy-safe for
        # concurrent shared appenders: serialize it separately
        self._pc_lock = threading.Lock()
        self._dtm_local = threading.local()
        # streaming ingest plane (runtime/ingest.py): long-lived COPY
        # streams committing micro-batches through the write-intent path
        from greengage_tpu.runtime.ingest import StreamIngestor

        self.ingest = StreamIngestor(self)
        # control-channel liveness: the channel reads its deadlines live
        # from THIS session's settings (SET mh_* applies immediately), and
        # the coordinator heartbeats workers between statements so an
        # idle-time partition is caught before the next dispatch
        if multihost is not None and multihost.channel is not None:
            multihost.channel.settings = self.settings
            if multihost.is_coordinator:
                try:
                    multihost.channel.start_heartbeat()
                except Exception as e:
                    self.log.error("multihost", f"heartbeat start failed: {e}")

    def _motion_overlap_factor(self) -> float:
        """Redistribute overlap credit from the motion_pipeline* GUCs
        (planner/cost.set_motion_overlap). The host bucket pipeline alone
        hides a modest slice of each exchange behind neighboring compute;
        sub-exchange splitting deepens the device-timeline overlap — up to
        half the transfer hidden at the deepest split. Deliberately
        conservative: the credit shapes plan choice between motion
        strategies, it does not promise free transfers."""
        if not bool(getattr(self.settings, "motion_pipeline", True)):
            return 1.0
        nb = max(int(getattr(self.settings, "motion_pipeline_buckets", 1)), 1)
        if nb <= 1:
            return 0.9
        # 2 buckets -> 0.75, 4 -> 0.625, >=8 -> floors at 0.5625
        return max(0.5, 0.5 + 0.5 / min(nb, 8))

    def _apply_xla_cache_dir(self) -> None:
        """Arm jax's persistent compilation cache from the xla_cache_dir
        GUC (per-platform subdirs keep TPU/CPU AOT entries apart — mixed
        entries trip feature-mismatch loads). No-op when the operator set
        GGTPU_XLA_CACHE explicitly (the import-time default honored it)."""
        if os.environ.get("GGTPU_XLA_CACHE") is not None:
            return
        path = (getattr(self.settings, "xla_cache_dir", "") or "").strip()
        if not path:
            return
        plat = (os.environ.get("GGTPU_PLATFORM")
                or os.environ.get("JAX_PLATFORMS") or "default")
        full = os.path.join(os.path.expanduser(path), plat)
        try:
            import jax

            if getattr(jax.config, "jax_compilation_cache_dir", None) != full:
                jax.config.update("jax_compilation_cache_dir", full)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.2)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:
            self.settings_warnings.append(f"xla_cache_dir not applied: {e}")
            return
        self._prune_xla_cache(full)

    def _prune_xla_cache(self, full: str) -> None:
        """jax's persistent cache (0.4.x) never evicts, and with
        min_entry_size=0 nearly every program persists — without a bound a
        long-lived workstation grows ~/.cache/ggtpu_xla indefinitely.
        Evict oldest-used first until under xla_cache_limit_mb."""
        limit_mb = int(getattr(self.settings, "xla_cache_limit_mb", 2048))
        if limit_mb <= 0:
            return
        try:
            with os.scandir(full) as it:
                ents = [(e.path, e.stat()) for e in it if e.is_file()]
        except OSError:
            return
        total = sum(st.st_size for _p, st in ents)
        if total <= limit_mb * (1 << 20):
            return
        ents.sort(key=lambda ps: max(ps[1].st_atime, ps[1].st_mtime))
        for p, st in ents:
            try:
                os.unlink(p)
            except OSError:
                continue
            total -= st.st_size
            if total <= limit_mb * (1 << 20):
                break

    @property
    def dtm(self):
        """The calling thread's transaction session (lazily created)."""
        d = getattr(self._dtm_local, "dtm", None)
        if d is None:
            d = self._DtmSession(self.store)
            self._dtm_local.dtm = d
        return d

    def abort_if_active(self) -> None:
        """Roll back the calling thread's open transaction, if any — the
        server calls this when a connection drops mid-transaction."""
        cur = self.dtm.current
        if cur is not None and cur.state == "active":
            with self._write_lock:
                self.dtm.abort()

    def _load_extensions(self) -> None:
        """Best-effort: a recorded extension whose module is gone must not
        brick the cluster (PG opens the database and errors at use); its
        functions simply stay unknown."""

        from greengage_tpu import extensions as X

        for name in self.catalog.extensions:
            try:
                X.load(name, cluster_path=self.path)
            except ValueError as e:
                warnings.warn(f"extension {name!r} failed to load: {e}")

    # ------------------------------------------------------------------
    def sql(self, text: str):
        """Execute one or more statements; returns the last statement's
        Result (or a status string for DDL/DML).

        Every call registers a StatementContext in the process-wide
        interrupt registry (runtime/interrupt.py) — the backend-entry
        CHECK_FOR_INTERRUPTS arming: `gg cancel`, statement_timeout_s,
        the runaway cleaner, and client disconnects all set its flag, and
        the statement dies at its next cancellation point with a typed
        cause. Nested calls (recursive-CTE fixpoints, retry redispatch)
        share the outermost statement's context."""
        ctx, _outer = _INTERRUPTS.enter(
            text, timeout_s=float(self.settings.statement_timeout_s))
        # statement trace (runtime/trace.py, the gpperfmon query-detail
        # role): trace id == statement id, so `gg ps` ids address `gg
        # trace` directly; nested calls share the outermost trace
        tr, t_outer = _TRACES.enter(
            ctx.statement_id, text,
            enabled=bool(getattr(self.settings, "trace_enabled", True)),
            ring_size=int(getattr(self.settings, "trace_ring_size", 64)))
        # per-statement memory account (runtime/memaccount.py, the
        # memaccounting.c owner tree): staging/block-cache/spill/device
        # charges land here; dumped on OOM, served by `gg mem`
        acct, a_outer = _memaccount.ACCOUNTS.enter(
            ctx.statement_id, text,
            enabled=bool(getattr(self.settings,
                                 "mem_accounting_enabled", True)))
        t0 = time.monotonic()
        root = (tr.begin("statement", cat="statement")
                if tr is not None and t_outer else None)
        if t_outer:
            # slow-log digest source: _cached_plan stashes the bound plan
            # here; cleared per statement so a slow DML can't pick up the
            # previous SELECT's digest
            self._pc_info_local.planned = None
            # memory-pressure brownout (runtime/overload.py): evaluate
            # the process-wide controller once per outermost statement
            # (rate-limited inside) and apply edge effects
            self._overload_tick()
        try:
            return self._sql_inner(text)
        except StatementCancelled as e:
            # one count (and one log line) per cancelled statement,
            # whichever cancellation point raised — the
            # statements_cancelled_<cause> family; _sql_inner's generic
            # error logging skips cancellations so this is the only row
            if not ctx.counted:
                ctx.counted = True
                _counters.inc(f"statements_cancelled_{e.cause}")
                if self.settings.log_statement:
                    self.log.error("statement",
                                   f"{e} [cause={e.cause}] -- in: "
                                   f"{text.strip()[:200]}")
            raise
        except OutOfDeviceMemory as e:
            # OOM forensics (memaccounting.c's OOM owner-tree dump):
            # mem-<id>.json beside the slow-log traces, carrying the full
            # per-owner accounting snapshot + the offending executable's
            # memory analysis
            if a_outer:
                self._dump_mem_forensics(e, ctx.statement_id, text)
            raise
        finally:
            if root is not None:
                tr.end(root)
            if t_outer:
                dur_ms = (time.monotonic() - t0) * 1e3
                _histograms.observe("statement_ms", dur_ms)
                self._maybe_log_slow(text, dur_ms, ctx.statement_id)
            _memaccount.ACCOUNTS.exit(acct)
            _TRACES.exit(tr)
            _INTERRUPTS.exit(ctx)

    def _overload_tick(self) -> None:
        """Brownout edge application (docs/ROBUSTNESS.md "Overload
        protection"): evaluate the process-wide controller and, on a
        transition this Database has not yet seen, apply the per-database
        effects — prompt block-cache eviction to the shrunken budget on
        enter (limit_bytes already reads the brownout factor; eviction
        would otherwise wait for the next insert) — and log the edge.
        Never raises: overload protection must not fail the statement it
        is protecting."""
        try:
            state = _overload.CONTROLLER.evaluate(self.settings)
            if state == self._overload_seen:
                return
            self._overload_seen = state
            snap = _overload.CONTROLLER.snapshot()
            with _trace.span("brownout-transition", cat="overload",
                             entered=state):
                self.store.blockcache.evict_to_fit()
            if state:
                self.log.log(
                    "WARNING", "overload",
                    f"brownout entered: {snap.get('reason')} — "
                    f"block-cache budget x{snap.get('cache_factor')}, "
                    "batch serving disabled, admissions prefer the "
                    "spill tier")
            else:
                self.log.info(
                    "overload",
                    "brownout cleared: pressure below the exit "
                    "threshold for brownout_exit_s")
        except Exception:
            pass

    def _maybe_log_slow(self, text: str, dur_ms: float,
                        statement_id: int) -> None:
        """Slow-statement log (log_min_duration_statement analog): any
        statement at/above log_min_duration_ms writes one slow_statement
        row carrying the plan digest and trace id, and exports the trace
        JSON beside the CSV logs for post-mortems (`gg trace` serves the
        same ring entry while the process lives). Never raises — logging
        must not take the query path down."""
        try:
            lm = float(getattr(self.settings, "log_min_duration_ms", -1.0))
            if lm < 0 or dur_ms < lm:
                return
            # digest from the plan this statement ACTUALLY bound (stashed
            # by _cached_plan) — never re-enter the plan cache here: a
            # plan_hash() call would double-count plan_cache_hit/miss,
            # record spurious spans, and on an evicted entry re-plan
            # (scalar subqueries included) on the query path
            digest = None
            planned = getattr(self._pc_info_local, "planned", None)
            if planned is not None:
                from greengage_tpu.planner.logical import describe as _desc

                digest = hashlib.sha1(
                    _desc(planned).encode()).hexdigest()[:16]
            _counters.inc("slow_statements")
            self.log.log(
                "WARNING", "slow_statement",
                f"duration {dur_ms:.1f} ms >= log_min_duration_ms={lm:g} "
                f"[trace={statement_id} plan={digest or '-'}]: "
                f"{text.strip()[:200]}",
                duration_ms=dur_ms)
            tr = _TRACES.current()
            if tr is not None and self.log.enabled:
                # the registry sets dur_ms at exit (after this dump):
                # record the measured duration now so the exported JSON
                # carries it instead of null
                tr.dur_ms = dur_ms
                os.makedirs(os.path.join(self.path, "log"), exist_ok=True)
                path = os.path.join(self.path, "log",
                                    f"trace-{statement_id}.json")
                with open(path, "w") as f:
                    _json.dump(_trace.to_chrome(tr), f)
        except Exception:
            pass

    def _dump_mem_forensics(self, e: OutOfDeviceMemory,
                            statement_id: int, text: str) -> None:
        """Write ``mem-<statement id>.json`` beside the slow-log traces
        (<cluster>/log): the per-owner accounting tree, the offending
        executable's memory_analysis, the admission estimate, and the
        live device stats at failure. Never raises — forensics must not
        replace the typed error the client is owed."""
        try:
            if not self.log.enabled:
                return
            payload = {
                "statement_id": statement_id,
                "sql": text.strip()[:500],
                "error": str(e),
                "est_bytes": e.est_bytes,
                "memory_analysis": e.mem_analysis,
                "accounting": e.snapshot,
                "ts_unix_s": round(time.time(), 3),
            }
            os.makedirs(os.path.join(self.path, "log"), exist_ok=True)
            path = os.path.join(self.path, "log",
                                f"mem-{statement_id}.json")
            with open(path, "w") as f:
                _json.dump(payload, f, indent=1, default=str)
            self.log.error("out_of_device_memory",
                           f"{e} [mem dump={path}]")
        except Exception:
            pass

    def _sql_inner(self, text: str):
        if self.multihost is not None and self.multihost.is_coordinator:
            return self._coordinator_sql(text)
        out = None
        with _trace.span("parse", cat="sql"):
            stmts = parse(text)
        for i, stmt in enumerate(stmts):
            # per-statement attribution even in a multi-statement batch
            what = text.strip() if len(stmts) == 1 else \
                f"[{i + 1}/{len(stmts)} {type(stmt).__name__}] {text.strip()}"
            t0 = time.monotonic()
            try:
                out = self._execute(stmt)
            except Exception as e:
                # cancellations log once in sql()'s handler, with cause
                if self.settings.log_statement \
                        and not isinstance(e, StatementCancelled):
                    self.log.error("statement", f"{e} -- in: {what}",
                                   duration_ms=(time.monotonic() - t0) * 1e3)
                raise
            if self.settings.log_statement:
                self.log.info(
                    "statement", what,
                    duration_ms=(time.monotonic() - t0) * 1e3,
                    rows=(len(out) if hasattr(out, "columns") else None))
            if self.settings.archive_mode and self.settings.archive_dir \
                    and isinstance(stmt, (
                        A.CreateTableStmt, A.DropTableStmt, A.AlterTableStmt,
                        A.CreateExternalTableStmt, A.CreateExtensionStmt,
                        A.ResourceGroupStmt, A.CreateIndexStmt,
                        A.DropIndexStmt)):
                # DDL moves the catalog without a manifest commit: refresh
                # the archived catalog copy (write paths archive via
                # _post_commit)
                from greengage_tpu.storage.archive import Archive

                try:
                    Archive(self.settings.archive_dir).archive_now(
                        self.path, self.store)
                except Exception as e:
                    self.log.error("archive", f"archiving failed: {e}")
        return out

    # ---- multi-host statement protocol (parallel/multihost.py) ---------
    @staticmethod
    def _needs_mesh(stmt) -> bool:
        if isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
            return True
        if isinstance(stmt, A.ExplainStmt):
            return stmt.analyze
        if isinstance(stmt, A.DeleteStmt):
            return stmt.where is not None
        if isinstance(stmt, A.DeclareCursorStmt):
            return True   # the DECLARE runs the mesh program
        return isinstance(stmt, A.UpdateStmt)

    def plan_hash(self, text_or_stmt) -> str | None:
        """Deterministic digest of the plan a SELECT-shaped statement
        produces here (structure + column ids + loci + row estimates):
        the coordinator attaches it to every mesh broadcast and workers
        verify theirs matches BEFORE entering the collectives — the
        lockstep assertion VERDICT r3 #8 asked for. None when the
        statement has no single pre-plannable query."""

        from greengage_tpu.planner.logical import describe

        stmt = (parse(text_or_stmt)[0] if isinstance(text_or_stmt, str)
                else text_or_stmt)
        if isinstance(stmt, A.DeclareCursorStmt):
            stmt = stmt.query
        if not isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
            return None
        if isinstance(stmt, A.SelectStmt) and not stmt.from_:
            return None
        # planning errors propagate: on the coordinator they fail the
        # statement BEFORE the broadcast; on a worker they fail the
        # readiness ack — swallowing them here would let a worker that
        # cannot re-plan enter (and hang) the collectives
        planned, _, _, _ = self._cached_plan(stmt)
        return hashlib.sha1(describe(planned).encode()).hexdigest()[:16]

    # ---- topology state (degraded <-> N-1 <-> full) --------------------
    def mh_state(self) -> dict:
        """The dispatch topology as `gg ps` / the server status frame show
        it: full (whole gang serving), n-1 (re-formed over survivors),
        degraded (single-process fallback), or local (no multihost)."""
        segs = self.catalog.segments
        if self.multihost is None or self.multihost.channel is None \
                or not self.multihost.is_coordinator:
            out = {"state": "local", "topology_version": segs.version}
            self._mh_state_standby(out)
            return out
        ch = self.multihost.channel
        if getattr(self, "_mh_degraded", None):
            state = "degraded"
        elif hasattr(ch, "is_partial") and ch.is_partial():
            state = "n-1"
        else:
            state = "full"
        out = {"state": state, "topology_version": segs.version,
               "expected_workers": getattr(ch, "expected_workers", None),
               "active_workers": (len(ch.active_ids())
                                  if hasattr(ch, "active_ids") else None)}
        if getattr(self, "_mh_degraded", None):
            out["reason"] = self._mh_degraded
        self._mh_state_standby(out)
        return out

    def _mh_state_standby(self, out: dict) -> None:
        """Attach the registered standby's replication health (path, lag
        in commits, cumulative ship failures) so `gg ps` / the status
        frame surface a silently-failing sync instead of hiding it."""
        from greengage_tpu.runtime import standby as _standby
        from greengage_tpu.runtime.logger import counters as _c

        sb = _standby.registered_standby(self.path)
        if sb is None:
            return
        out["standby"] = {
            "path": sb,
            "lag_commits": _standby.lag(self.path),
            "sync_fail_total": int(_c.snapshot().get(
                "standby_sync_fail_total", 0)),
        }

    def _mh_distributed_active(self) -> bool:
        """True when a jax.distributed data plane is live: its global mesh
        cannot re-form over survivors without a runtime re-init, so worker
        death must take the degraded path there. Control-plane-only gangs
        (each process owns its full local mesh; this environment's mode)
        re-form freely — pjit resolves the mesh at call site, so cached
        executables re-bind without recompiling."""
        try:
            from jax._src import distributed as _dist

            return _dist.global_state.client is not None
        except Exception:
            return False

    def _mh_worker_lost(self, reason: str, dead_pid=None) -> None:
        """Topology failover entry: a worker died/hung. Prefer N-1 mesh
        re-formation over the survivors (the cdbgang shrink + mirror
        promotion the reference performs); fall back to the degraded
        single-process path when re-formation is disabled, impossible
        (live jax.distributed data plane), or fails."""
        if getattr(self, "_mh_degraded", None):
            return
        if self.settings.mh_reform_enabled \
                and not self._mh_distributed_active():
            if self._mh_reform(reason, dead_pid):
                return
        self._mh_degrade(reason)

    def _mh_reform(self, reason: str, dead_pid=None) -> bool:
        """Re-form the gang over the SURVIVORS (N-1): quiesce the channel
        (survivors redial the kept listener within seconds — worker_loop
        treats the teardown as a lost coordinator and reconnects), promote
        cross-host mirror roots for contents whose storage died with the
        worker, bump the topology version, adopt whoever redialed before
        mh_reform_deadline_s, and replay the settings/topology sync. The
        re-formed gang serves every later statement — DML included, since
        manifest commits are coordinator-local — and the kept listener
        plus the rejoin accept loop restore full strength when the lost
        worker returns."""
        from greengage_tpu.parallel.multihost import WorkerDied
        from greengage_tpu.runtime.faultinject import FaultError, faults
        from greengage_tpu.runtime.retry import Deadline

        ch = self.multihost.channel
        if not hasattr(ch, "adopt_pending"):
            return False
        try:
            faults.check("mesh_reform")
        except FaultError as e:
            self.log.error("multihost", f"mesh re-formation failed "
                                        f"(fault injected): {e}")
            return False
        who = f"worker {dead_pid}" if dead_pid is not None else "a worker"
        self.log.error("multihost",
                       f"{who} lost; re-forming the gang over survivors: "
                       f"{reason}")
        survivors_want = max(0, len(ch.active_ids()) - 1)
        try:
            ch.quiesce()
        except Exception as e:
            self.log.error("multihost", f"quiesce failed: {e}")
            return False
        # mirror promotion over surviving storage (ftsprobe.c:968 role):
        # probe every content NOW — one whose primary tree died with the
        # worker's host gets its in-sync cross-host mirror promoted, so
        # the N-1 topology serves every content from a surviving root
        try:
            faults.check("mirror_promote_during_reform")
            if self.catalog.segments.has_mirrors():
                self.fts.probe_once()
        except FaultError as e:
            self.log.error("multihost",
                           f"mirror promotion during re-formation failed "
                           f"(fault injected): {e}")
            return False
        except Exception as e:
            self.log.error("multihost", f"re-formation FTS probe failed: {e}")
        # the FTS-version bump: cached dispatch topology is invalid, and
        # rejoining workers must observe this exact version in the sync
        self.catalog.segments.version += 1
        try:
            self.catalog._save()
        except Exception as e:
            self.log.error("multihost", f"topology save failed: {e}")
        dl = Deadline(float(self.settings.mh_reform_deadline_s))
        while ch.pending_count() < survivors_want and not dl.expired:
            # re-formation must run to completion-or-fallback even when
            # the triggering statement was cancelled: aborting mid-reform
            # leaves a half-promoted topology no later statement can use.
            # Bounded by mh_reform_deadline_s.
            time.sleep(0.02)   # gg:ok(interrupts)
        ch.adopt_pending()
        try:
            self._mh_sync_gang(phase="reform sync")
        except (WorkerDied, RuntimeError, OSError) as e:
            self.log.error("multihost", f"gang re-formation failed: {e}")
            try:
                ch.quiesce()
            except Exception:
                pass
            return False
        self._mh_degraded = None
        _counters.inc("mh_reform_total")
        _counters.set("mh_topology_version", self.catalog.segments.version)
        try:
            ch.start_heartbeat()
        except Exception:
            pass
        st = self.mh_state()
        self.log.info(
            "multihost",
            f"gang re-formed: {st['state']} with "
            f"{st['active_workers']}/{st['expected_workers']} workers "
            f"(topology v{st['topology_version']})")
        return True

    def _mh_sync_gang(self, phase: str = "rejoin sync") -> None:
        """Replay the settings + topology sync against the current gang;
        raises WorkerDied/RuntimeError when any member is gone or reports
        a stale topology version (shared directory out of sync)."""

        from greengage_tpu.parallel.multihost import WorkerDied

        payload = {f.name: getattr(self.settings, f.name)
                   for f in _dc.fields(self.settings)
                   if not f.name.startswith("_")}
        want_v = self.catalog.segments.version
        acks = self.multihost.channel.broadcast(
            {"op": "sync", "settings": payload, "topology_version": want_v},
            deadline="mh_ready_deadline", phase=phase)
        stale = [a for a in acks if a.get("topology_version") != want_v]
        if stale:
            raise WorkerDied(
                f"rejoined worker reports topology version "
                f"{stale[0].get('topology_version')}, coordinator has "
                f"{want_v} — shared directory out of sync")

    def _mh_try_restore_full(self) -> None:
        """While an N-1 gang serves, the lost worker may redial the kept
        listener at any time; adopting it restores the full topology.
        Called at each statement boundary — cheap (one lock + len)."""
        from greengage_tpu.parallel.multihost import WorkerDied

        ch = self.multihost.channel
        if not hasattr(ch, "pending_count") or not hasattr(ch, "is_partial"):
            return
        if not ch.is_partial() or ch.pending_count() == 0:
            return
        ch.adopt_pending()
        self.catalog.segments.version += 1
        try:
            self.catalog._save()
        except Exception:
            pass
        try:
            self._mh_sync_gang(phase="restore sync")
        except (WorkerDied, RuntimeError, OSError) as e:
            # the rejoiner (or a survivor) is unusable: fall back to a
            # fresh re-formation over whoever still answers
            self._mh_worker_lost(f"gang restore failed: {e}")
            return
        _counters.set("mh_topology_version", self.catalog.segments.version)
        st = self.mh_state()
        self.log.info(
            "multihost",
            f"gang restored: {st['state']} with "
            f"{st['active_workers']}/{st['expected_workers']} workers "
            f"(topology v{st['topology_version']})")

    def _mh_degrade(self, reason: str) -> None:
        """A worker died: the global device mesh can no longer rendezvous.
        Mark the cluster degraded — every later mesh statement re-forms as
        a single-process session over the SHARED cluster directory (which
        holds every segment's storage) in a subprocess, the
        mirror-failover analog for a lost compute host."""
        self._mh_degraded = reason
        self.log.error("multihost", f"worker lost; degraded to local: {reason}")
        # re-form the topology over surviving storage (ftsprobe.c:968
        # role): probe every content NOW — a content whose primary tree
        # died with the worker's host gets its in-sync mirror promoted,
        # so the re-formed service answers from the mirror trees (which
        # cross-host placement keeps on surviving roots)
        try:
            if self.catalog.segments.has_mirrors():
                self.fts.probe_once()
                self.catalog._save()
        except Exception as e:
            self.log.error("multihost", f"post-death FTS probe failed: {e}")
        # quiesce, don't close: worker connections tear down but the
        # listener stays open so a restarted/woken worker can rejoin and
        # the gang can re-form (docs/ROBUSTNESS.md, _mh_try_recover)
        try:
            self.multihost.channel.quiesce()
        except Exception:
            pass
        # detach the distributed runtime WITHOUT the shutdown barrier: it
        # can never complete against a dead peer — calling shutdown()
        # blocks for the barrier timeout, and leaving it for atexit turns
        # a served degradation into a crash at interpreter exit. Dropping
        # the handles makes both a no-op; the stashed references keep the
        # C++ objects from running disconnect destructors mid-session.
        try:
            from jax._src import distributed as _dist

            self._mh_detached = (_dist.global_state.client,
                                 _dist.global_state.service)
            _dist.global_state.client = None
            _dist.global_state.service = None
        except Exception:
            pass

    def mh_try_recover(self) -> bool:
        """Gang recovery (the cdbgang re-formation role): while DEGRADED,
        adopt the fully-reconnected gang and leave degraded mode; while an
        N-1 partial gang serves, adopt a rejoined worker back to full
        strength. Safe to call any time; also attempted automatically at
        each statement. True when mesh dispatch is available (full or
        N-1)."""
        if self.multihost is None or not self.multihost.is_coordinator:
            return False
        if not getattr(self, "_mh_degraded", None):
            self._mh_try_restore_full()
            return True
        return self._mh_try_recover()

    def _mh_try_recover(self) -> bool:
        from greengage_tpu.parallel.multihost import WorkerDied

        ch = self.multihost.channel
        if not (hasattr(ch, "rejoin_ready") and ch.rejoin_ready()):
            return False
        # settle the topology BEFORE workers re-plan against it: probe now
        # (promotions during the degraded window persist), then require
        # every rejoined worker to report the same topology version — the
        # FTS-version check the reference dispatcher runs per gang
        if self.catalog.segments.has_mirrors():
            try:
                self.fts.probe_once()
                self.catalog._save()
            except Exception as e:
                self.log.error("multihost", f"pre-rejoin FTS probe failed: {e}")
        try:
            ch.adopt_rejoined()
            self._mh_sync_gang(phase="rejoin sync")
        except (WorkerDied, RuntimeError, OSError) as e:
            self.log.error("multihost", f"gang rejoin failed: {e}")
            try:
                ch.quiesce()   # back to accepting reconnections
            except Exception:
                pass
            return False
        # restore the distributed-runtime handles stashed at degrade (the
        # data plane was never torn down — a hung-then-recovered worker's
        # collectives can rendezvous again)
        if getattr(self, "_mh_detached", None) is not None:
            try:
                from jax._src import distributed as _dist

                (_dist.global_state.client,
                 _dist.global_state.service) = self._mh_detached
            except Exception:
                pass
            self._mh_detached = None
        self._mh_degraded = None
        try:
            ch.start_heartbeat()
        except Exception:
            pass
        _counters.set("mh_topology_version", self.catalog.segments.version)
        self.log.info("multihost",
                      f"gang recovered: mesh dispatch restored "
                      f"(topology v{self.catalog.segments.version})")
        return True

    def cluster_inject_fault(self, name: str, type: str = "error",
                             segment: int | None = None, occurrences: int = 1,
                             sleep_s: float = 0.1, start_after: int = 0,
                             reset: bool = False) -> list[dict]:
        """gp_inject_fault dispatched to segments: arm (or reset) a named
        fault point in every WORKER process over the control channel.
        Coordinator-side points are armed directly via
        runtime.faultinject.faults."""
        if self.multihost is None or not self.multihost.is_coordinator \
                or getattr(self, "_mh_degraded", None):
            raise SqlError("cluster_inject_fault needs a non-degraded "
                           "multihost coordinator")
        return self.multihost.channel.broadcast(
            {"op": "fault", "name": name, "type": type, "segment": segment,
             "occurrences": occurrences, "sleep_s": sleep_s,
             "start_after": start_after, "reset": reset},
            deadline="mh_ready_deadline", phase="fault")

    def _degraded_sql(self, text: str):
        """Serve one statement from a fresh single-process subprocess over
        the shared directory (all segments local). Transactions cannot
        span subprocesses; everything else completes with full results."""

        if self.dtm.current is not None and self.dtm.current.state == "active":
            raise SqlError("cluster is degraded (worker died); transactions "
                           "cannot continue — ROLLBACK and retry")
        if any(isinstance(st, A.DeclareCursorStmt) for st in parse(text)):
            # a cursor declared in the throwaway subprocess would vanish
            # before RETRIEVE: refuse instead of reporting false success
            raise SqlError("parallel retrieve cursors are unavailable while "
                           "the cluster is degraded")
        child = (
            "import os, sys, json\n"
            "os.environ['GGTPU_PLATFORM'] = 'cpu'\n"
            "flags = [f for f in os.environ.get('XLA_FLAGS', '').split()\n"
            "         if 'host_platform_device_count' not in f]\n"
            "flags.append('--xla_force_host_platform_device_count=%d')\n"
            "os.environ['XLA_FLAGS'] = ' '.join(flags)\n"
            "sys.path.insert(0, %r)\n"
            "import greengage_tpu\n"
            "db = greengage_tpu.connect(%r, numsegments=%d)\n"
            "r = db.sql(sys.stdin.read())\n"
            "def enc(x):\n"
            "    try:\n"
            "        import numpy as np\n"
            "        if isinstance(x, np.generic): x = x.item()\n"
            "    except Exception: pass\n"
            "    return x if isinstance(x, (int, float, str, bool,\n"
            "                               type(None))) else str(x)\n"
            "if isinstance(r, str):\n"
            "    print('DEGRADED:' + json.dumps({'status': r}), flush=True)\n"
            "else:\n"
            "    print('DEGRADED:' + json.dumps(\n"
            "        {'columns': list(r.columns),\n"
            "         'rows': [[enc(x) for x in row] for row in r.rows()]}),\n"
            "        flush=True)\n"
        ) % (self.numsegments,
             os.path.dirname(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))),
             self.path, self.numsegments)
        proc = subprocess.run(
            [_sys.executable, "-c", child], input=text, text=True,
            capture_output=True, timeout=900)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("DEGRADED:")]
        if proc.returncode != 0 or not lines:
            raise QueryError(
                f"degraded execution failed (rc={proc.returncode}): "
                f"{proc.stderr[-800:]}")
        payload = _json.loads(lines[-1][len("DEGRADED:"):])
        if "status" in payload:
            return payload["status"]
        return _DegradedResult(payload["columns"], payload["rows"])

    @staticmethod
    def _is_read_only(stmt) -> bool:
        """The dispatcher's retryable classification: statements that
        never touch the manifest/catalog may be transparently redispatched
        after a dispatch failure; anything else is a write and the DTM's
        exactly-once guarantee decides (= no auto-retry)."""
        return isinstance(stmt, (A.SelectStmt, A.UnionStmt, A.ExplainStmt,
                                 A.DeclareCursorStmt))

    def _dispatch_failover(self, stmt, text: str, err, is_retry: bool):
        """A worker died/hung BEFORE anyone entered a collective, so the
        statement never ran. Read-only statements retry transparently
        ONCE: when the gang already re-formed over survivors (N-1 path),
        redispatch immediately; while DEGRADED, wait up to
        mh_retry_window_s for recovery first and otherwise complete on
        the degraded local path as before. Write statements surface the
        error without re-execution: the commit record was never written,
        so nothing committed, and only an explicit client retry (or a
        LATER statement) may run it — exactly-once is the DTM's to keep,
        never the dispatcher's to gamble."""
        from greengage_tpu.runtime.faultinject import faults
        from greengage_tpu.runtime.retry import Deadline

        if not self._is_read_only(stmt):
            raise QueryError(
                f"worker died mid-dispatch; write statement was NOT "
                f"auto-retried (nothing committed — retry explicitly if "
                f"desired): {err}")
        window = float(self.settings.mh_retry_window_s)

        def redispatch():
            # the window a test can force open/shut: sleep widens
            # the race, error fails the redispatch path itself
            faults.check("retry_redispatch")
            _counters.inc("statements_retried")
            self.log.info(
                "statement",
                f"gang re-formed; redispatching read-only "
                f"statement after dispatch failure: "
                f"{text.strip()[:160]}")
            return self._coordinator_sql(text, _is_retry=True)

        # window 0 disables transparent redispatch ENTIRELY — even when an
        # N-1 re-formation already re-bound the gang (the operator opted
        # out of re-executing reads, not just out of waiting)
        if not is_retry and window > 0 \
                and not getattr(self, "_mh_degraded", None):
            return redispatch()     # N-1 re-formation already re-bound
        if not is_retry and window > 0:
            dl = Deadline(window)
            while True:
                if self.mh_try_recover():
                    return redispatch()
                if dl.expired:
                    break
                # retry-window wait = a cancellation point: a cancelled
                # statement must not sit out the full window first
                check_interrupts()
                time.sleep(0.05)
        return self._degraded_sql(text)

    def _coordinator_sql(self, text: str, _is_retry: bool = False):
        """Host-only statements run locally (workers pick the effects up
        from the shared directory at their next refresh). Mesh statements
        run a TWO-PHASE dispatch: broadcast with the coordinator's plan
        hash, collect readiness acks (workers verified the hash and are
        parked before the collectives), then 'go' and execute here
        CONCURRENTLY with the workers. A dead worker surfaces on the
        channel during the readiness round — BEFORE anyone enters a
        collective that could never rendezvous — and the statement fails
        over by class: read-only statements transparently redispatch once
        after gang re-formation (else complete on the degraded local
        path); writes surface the error (_dispatch_failover)."""
        from greengage_tpu.parallel.multihost import WorkerDied

        ch = self.multihost.channel
        # idle-time liveness: the heartbeat thread marks the channel dead
        # on a missed pong — re-form/degrade HERE, before wasting a
        # broadcast on a partitioned gang (and before _execute could
        # enter a collective)
        if not getattr(self, "_mh_degraded", None) \
                and getattr(ch, "hb_failure", None):
            self._mh_worker_lost(f"heartbeat liveness check failed: "
                                 f"{ch.hb_failure}")
        # gang recovery: once the full gang has reconnected, re-sync and
        # fall through to normal mesh dispatch below
        if getattr(self, "_mh_degraded", None) and not self._mh_try_recover():
            stmts = parse(text)
            if any(self._needs_mesh(st) for st in stmts):
                return self._degraded_sql(text)
            out = None
            for stmt in stmts:
                out = self._execute(stmt)
            return out
        # N-1 partial gang: adopt the lost worker back the moment it has
        # redialed the kept listener (full-strength restoration)
        if not getattr(self, "_mh_degraded", None):
            self._mh_try_restore_full()
        stmts = parse(text)
        if any(getattr(st, "_recursive_ctes", None) for st in stmts):
            raise SqlError(
                "WITH RECURSIVE is not supported in multi-host mode yet "
                "(the fixpoint iteration cannot run under mesh lockstep)")
        mesh_stmts = [st for st in stmts if self._needs_mesh(st)]
        if mesh_stmts and len(stmts) > 1:
            raise SqlError(
                "multi-host mode runs one mesh statement (SELECT/DML) per "
                "sql() call; split the statement batch")
        out = None
        for stmt in stmts:
            if self._needs_mesh(stmt):
                # vectorized serving on the gang: an eligible SELECT
                # enrolls in the batch window BEFORE the per-statement
                # two-phase dispatch — the flush broadcasts the whole
                # window (op sql_batch) instead. None = not eligible or
                # the batch fell back; continue on the classic dispatch.
                if isinstance(stmt, A.SelectStmt):
                    bres = self._mh_batch_try(stmt, text)
                    if bres is not None:
                        out = bres
                        continue
                # coordinator-side validation AND queue admission BEFORE
                # the broadcast: a host-side rejection or queue wait after
                # workers enter the collectives would deadlock the cluster
                if isinstance(stmt, (A.DeleteStmt, A.UpdateStmt)):
                    self._check_no_raw_dml(stmt.table)
                    self._tx_for_dml(stmt.table, type(stmt).__name__[:6].upper())
                if isinstance(stmt, A.DeclareCursorStmt):
                    self._validate_declare(stmt)
                # one exchange()-scoped lock covers the whole two-phase
                # dispatch, so the heartbeat thread can never interleave
                # frames mid-statement; every ack round is deadline-
                # bounded (a hung worker classifies as WorkerDied within
                # mh_ready/ack_deadline, never an unbounded readline).
                # The WorkerDied handler sits OUTSIDE the admission scope
                # so a retry redispatch re-admits on a released slot.
                # The whole exchange is the statement's DISPATCH span:
                # worker-side spans arrive in the completion acks and
                # graft under it, so one trace shows the whole cluster
                _tr = _TRACES.current()
                _disp = (_tr.begin("dispatch", cat="multihost")
                         if _tr is not None else None)
                _comp_acks = None
                try:
                    with self._admission():
                        with ch.exchange():
                            # calibration rides the dispatch frame: the
                            # workers adopt OUR applied scales before
                            # re-planning, so corrected estimates never
                            # break the plan-hash lockstep invariant
                            ch.send({"op": "sql", "sql": text,
                                     "plan_hash": self.plan_hash(stmt),
                                     "fb": self.feedback.wire_payload()})
                            try:
                                ch.collect_acks(deadline="mh_ready_deadline",
                                                phase="readiness")
                            except StatementCancelled:
                                # cancelled while parked on readiness:
                                # nobody entered the mesh — release the
                                # parked workers and surface the typed
                                # cancellation
                                ch.send({"op": "skip"})
                                raise
                            except RuntimeError as e:
                                # a worker REFUSED (plan-hash mismatch or
                                # its planning failed): nobody entered the
                                # mesh — release the parked survivors and
                                # fail cleanly
                                ch.send({"op": "skip"})
                                raise QueryError(str(e))
                            ch.send({"op": "go"})
                            # arm spill-schedule recording: the workers
                            # ship theirs in the completion acks and the
                            # parity check below asserts lockstep
                            self.executor.begin_spill_schedule()
                            _sched = None
                            try:
                                out = self._execute(stmt)
                                _sched = \
                                    self.executor.collect_spill_schedule()
                            finally:
                                try:
                                    _acks = ch.collect_acks(
                                        deadline="mh_ack_deadline",
                                        phase="completion")
                                    _comp_acks = _acks
                                    if _disp is not None:
                                        _trace.graft_acks(_tr, _acks, _disp)
                                    if _sched is not None:
                                        # only when our side succeeded —
                                        # never mask an in-flight error
                                        self._mh_spill_parity(_sched, _acks)
                                except WorkerDied as e:
                                    # our side already finished its mesh
                                    # program: the result stands; later
                                    # statements run on the re-formed N-1
                                    # gang (or the degraded path)
                                    self._mh_worker_lost(
                                        str(e),
                                        getattr(e, "process_id", None))
                                except StatementCancelled:
                                    # a half-collected exchange cannot be
                                    # resumed (workers are still running
                                    # their program and will ack into the
                                    # teardown): quiesce so stale acks
                                    # never leak into the next statement;
                                    # the gang re-forms via rejoin
                                    self._mh_degrade(
                                        "statement cancelled while "
                                        "collecting completion acks")
                                    raise
                except WorkerDied as e:
                    # death/hang BEFORE anyone entered a collective
                    # (readiness or go phase): re-form over the survivors
                    # (or degrade), then fail over by statement class
                    # (reads redispatch, writes surface the error —
                    # exactly-once)
                    self._mh_worker_lost(str(e),
                                         getattr(e, "process_id", None))
                    return self._dispatch_failover(stmt, text, e, _is_retry)
                finally:
                    if _disp is not None:
                        _tr.end(_disp)
                # cluster-wide runaway verdict (the multihost
                # runaway_cleaner, VERDICT missing #7): one decision from
                # the AGGREGATED gang watermarks, enforced at the
                # statement completion boundary — raises RunawayCancelled
                self._mh_runaway_check(_comp_acks)
            else:
                if isinstance(stmt, A.SetStmt):
                    # settings steer MESH decisions (spill passes, retry
                    # tiers, fused kernel): workers must apply the same
                    # values or their lockstep branches desync. ONLY this
                    # statement ships (a batch re-parse on the worker
                    # would apply later statements the coordinator might
                    # never reach)
                    try:
                        with ch.exchange():
                            ch.send({"op": "set", "name": stmt.name,
                                     "value": stmt.value})
                            try:
                                out = self._execute(stmt)
                            finally:
                                ch.collect_acks(deadline="mh_ready_deadline",
                                                phase="set")
                    except WorkerDied as e:
                        # apply the SET locally FIRST, then re-form: the
                        # re-formation (or later rejoin) sync re-ships the
                        # whole settings payload, new value included
                        out = self._execute(stmt)
                        self._mh_worker_lost(str(e),
                                             getattr(e, "process_id", None))
                    continue
                out = self._execute(stmt)
        return out

    def worker_sql(self, text: str):
        """Run the DEVICE side of the coordinator's statement in lockstep
        (exec_mpp_query role): SELECT/EXPLAIN ANALYZE execute fully; write
        statements run only their internal mesh scans (DELETE/UPDATE read
        passes) — publishing is the coordinator's job."""
        for stmt in parse(text):
            if isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
                self._select(stmt)
            elif isinstance(stmt, A.DeclareCursorStmt):
                # RETRIEVE is host-side on the coordinator; the worker only
                # participates in the DECLARE's collectives. deferred=True
                # mirrors the coordinator exactly: same pre-collective
                # memory-ceiling behavior, and no wasted full-result
                # finalize/decode of a shard nobody reads
                planned, consts, outs, ek = self._cached_plan(stmt.query)
                try:
                    self.executor.run(planned, consts, outs, cache_key=ek,
                                      deferred=True)
                except QueryError as e:
                    if "duplicate keys" not in str(e):
                        raise
                    # deterministic lockstep with the coordinator's re-plan:
                    # both sides saw the same dup flag on the same data
                    planned, consts, outs, ek = self._cached_plan(
                        stmt.query, force_multi_join=True)
                    self.executor.run(planned, consts, outs, cache_key=ek,
                                      deferred=True)
            elif isinstance(stmt, A.ExplainStmt) and stmt.analyze:
                self._explain(stmt)
            elif isinstance(stmt, (A.DeleteStmt, A.UpdateStmt)):
                self._worker_dml_scan(stmt)
            # everything else is host-side work owned by the coordinator
            # (SET arrives as its own channel op, never via batch text)

    def _worker_dml_scan(self, stmt):
        """Reproduce the coordinator's internal raw SELECT so its mesh
        program has all participants (the plan is deterministic)."""
        if isinstance(stmt, A.DeleteStmt):
            self._delete(stmt, worker_scan_only=True)
        else:
            self._update(stmt, worker_scan_only=True)

    # ---- multihost serving parity (docs/PERF.md "Data movement") ------
    def _mh_batch_try(self, stmt, text: str):
        """Coordinator half of gang batch serving: enroll an eligible
        parameterized SELECT in the batch window BEFORE any per-statement
        broadcast; the BatchServer's flush broadcasts the whole window
        (op sql_batch) through _mh_batch_exchange so every gang member
        dispatches the same width-bucketed program. Returns the member's
        Result, or None (not eligible / window fell back) — the caller
        proceeds with the classic two-phase dispatch."""
        if not bool(getattr(self.settings, "batch_serving_enabled", False)):
            return None
        if not isinstance(stmt, A.SelectStmt) or not stmt.from_:
            return None
        if _overload.CONTROLLER.brownout_active():
            return None
        cur = self.dtm.current
        if cur is not None and cur.state == "active":
            return None
        try:
            planned, consts, outs, exec_key = self._cached_plan(stmt)
        except Exception:
            return None   # the classic path owns surfacing plan errors
        pc_info = self._plan_cache_info
        if (consts or {}).get("@params@") is None:
            return None
        aux, _dirty = self._load_external_aux(planned)
        if aux:
            return None   # external loads stay serial (per-member state)
        with self._admission():
            res = self._batcher().submit(planned, consts, outs, exec_key,
                                         consts["@params@"], sql=text,
                                         plan_hash=self.plan_hash(stmt))
        if res is not None:
            if isinstance(res.stats, dict):
                res.stats["plan_cache"] = dict(pc_info)
            self._record_stats(res)
        return res

    @_contextmanager
    def _mh_batch_exchange(self, sqls: list, plan_hash):
        """Two-phase broadcast of one batch window, called on the
        BatchServer's dispatcher thread (no statement context): readiness
        acks -> 'go' -> yield for the concurrent local dispatch ->
        completion acks. EVERY failure surfaces as BatchFallback — the
        members re-run through the classic per-statement dispatch, which
        owns retries and failover. Gang degradation is NOT handled here:
        this runs on the dispatcher thread, and _mh_degraded/_mh_detached
        belong to the statement role. A dead peer raises WorkerDied again
        on the first serial re-run's own broadcast, where _coordinator_sql
        re-forms the gang on a statement thread."""
        from greengage_tpu.exec.executor import BatchFallback
        from greengage_tpu.parallel.multihost import WorkerDied

        ch = self.multihost.channel
        if getattr(ch, "hb_failure", None):
            raise BatchFallback("gang unavailable for batched dispatch")
        try:
            with ch.exchange():
                ch.send({"op": "sql_batch", "sqls": list(sqls),
                         "plan_hash": plan_hash,
                         "fb": self.feedback.wire_payload()})
                try:
                    ch.collect_acks(deadline="mh_ready_deadline",
                                    phase="readiness")
                except RuntimeError as e:
                    # a worker REFUSED (hash mismatch / planning failed):
                    # nobody entered the mesh — release the parked
                    # survivors and serve the members serially
                    ch.send({"op": "skip"})
                    raise BatchFallback(
                        f"worker refused batch window: {e}")
                ch.send({"op": "go"})
                done = False
                try:
                    yield
                    done = True
                finally:
                    try:
                        ch.collect_acks(deadline="mh_ack_deadline",
                                        phase="completion")
                    except WorkerDied:
                        raise
                    except RuntimeError as e:
                        if done:
                            # a worker's batch failed where ours ran:
                            # fall back — the serial re-runs keep the
                            # gang in lockstep statement by statement
                            raise BatchFallback(
                                f"worker batch execution failed: {e}")
                        # local dispatch already raising: let it surface
        except WorkerDied as e:
            raise BatchFallback(f"worker lost during batched dispatch: {e}")

    def worker_sql_batch(self, sqls: list):
        """Worker half of gang batch serving: plan every member of the
        broadcast window (same plan cache, same literal hoisting), stack
        their parameter vectors, and run the SAME width-bucketed batched
        program the coordinator is dispatching concurrently."""
        from greengage_tpu.exec.executor import BatchFallback

        planned = consts = outs = ek = None
        pvecs = []
        for i, q in enumerate(sqls):
            stmt = parse(q)[0]
            p, c, o, k = self._cached_plan(stmt)
            pv = (c or {}).get("@params@")
            if pv is None:
                raise BatchFallback(
                    "window member did not parameterize on the worker")
            if i == 0:
                # the window's shared program compiles from the FIRST
                # member's bound plan, mirroring the coordinator's window
                planned, consts, outs, ek = p, c, o, k
            pvecs.append(pv)
        self.executor.run_batch(planned, consts, outs, ek, pvecs)

    def _mh_spill_parity(self, mine: list, acks) -> None:
        """Lockstep assertion for tiered-spill schedules: every worker
        ships the pass/bucket schedule it actually ran in its completion
        ack; divergence from the coordinator's means the gang's programs
        could not have rendezvoused deterministically. Tier placement
        (RAM vs disk) is deliberately absent from the schedule — it is
        host-local and MUST NOT affect parity."""
        for a in acks or []:
            ws = a.get("spill_schedule") if isinstance(a, dict) else None
            if ws is None:
                continue
            if list(ws) != list(mine):
                raise QueryError(
                    "spill-schedule parity violation: coordinator ran "
                    f"{mine} but worker {a.get('process_id')} ran {ws}")

    def _mh_runaway_check(self, acks) -> None:
        """Cluster-wide runaway verdict (the multihost runaway_cleaner):
        workers ship their HBM watermark in every completion ack (riding
        the span-shipping path), the coordinator adds its own device
        peak, and ONE decision covers the gang — when the aggregate
        crosses the red zone of vmem_global_limit_mb, cancellation
        broadcasts through every process's interrupt registry and the
        statement surfaces a typed RunawayCancelled to the client.
        Enforcement lands at the completion boundary: an XLA program
        cannot be preempted mid-flight, so the boundary after the gang's
        acks is the cluster's CHECK_FOR_INTERRUPTS."""
        limit = int(getattr(self.settings, "vmem_global_limit_mb", 0)) << 20
        if not limit or not acks:
            return
        from greengage_tpu.parallel.multihost import _hbm_watermark

        total = _hbm_watermark(self)   # the coordinator's own peak
        for a in acks:
            if isinstance(a, dict):
                total += int(a.get("hbm", 0) or 0)
        red = int(limit * float(getattr(self.settings,
                                        "runaway_red_zone", 0.9)))
        if total <= red:
            return
        reason = (f"cluster HBM watermark {total >> 20} MB above the "
                  f"red zone ({red >> 20} MB of vmem_global_limit_mb="
                  f"{limit >> 20} MB)")
        from greengage_tpu.runtime.faultinject import faults

        # 'skip' on this point suppresses the worker broadcast (verdict
        # still enforced locally) — the gang test's partial-failure probe
        if not faults.check("runaway_broadcast"):
            try:
                self.multihost.channel.broadcast(
                    {"op": "runaway", "reason": reason},
                    deadline="mh_ready_deadline", phase="runaway")
            except Exception:
                # a dead/hung worker must not shield the verdict; the
                # next statement's dispatch handles gang re-formation
                pass
        _counters.inc("statements_cancelled_runaway")
        ctx = _INTERRUPTS.current()
        if ctx is not None:
            ctx.cancel("runaway", reason)
            ctx.check()
        # no statement context (internal caller): raise the typed error
        from greengage_tpu.runtime.runaway import RunawayCancelled

        raise RunawayCancelled(reason)

    def refresh(self) -> None:
        """Adopt the coordinator's committed catalog/manifest state from
        the shared cluster directory (workers call this per statement).

        The bound-plan cache is cleared only when the adopted state
        actually CHANGED (catalog bytes or manifest version): paramized
        generic plans carry the row estimates of the literals they were
        first bound with, so a worker that re-binds every statement
        while the coordinator serves its cache would compute a different
        plan hash for every repeated shape with a new literal — the
        lockstep verifier would reject its own gang. Keeping the cache
        across unchanged refreshes makes both sides bind each shape
        once, in the same broadcast order, with the same literals."""
        self.catalog = Catalog.load(self.path)
        self._load_extensions()
        self.store.catalog = self.catalog
        self.numsegments = self.catalog.segments.numsegments
        self.executor.catalog = self.catalog
        state = (self.store.manifest.snapshot().get("version", 0),
                 self._catalog_fingerprint())
        if state != getattr(self, "_refresh_state", None) or None in state:
            self._select_cache.clear()
            self._refresh_state = state
        self.store._invalidate_dicts_all()

    def _catalog_fingerprint(self) -> str | None:
        """Digest of the on-disk catalog (None when unreadable): ANALYZE
        stats, index DDL, and partition changes all ride catalog.json
        without bumping the manifest version, and each must invalidate
        a worker's bound plans exactly like the coordinator's own clear
        sites do."""
        try:
            with open(os.path.join(self.path, "catalog.json"), "rb") as f:
                return hashlib.sha1(f.read()).hexdigest()
        except OSError:
            return None

    def _execute(self, stmt):
        if isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
            return self._select(stmt)
        if isinstance(stmt, A.ExplainStmt):
            return self._explain(stmt)
        if isinstance(stmt, A.RetrieveStmt):
            # read-only endpoint drain: the whole point is N retrieve
            # sessions draining concurrently — never behind the write lock
            return self._retrieve(stmt)
        if isinstance(stmt, A.DeclareCursorStmt):
            # read-only query; only the cursor-registry insert takes the
            # lock (inside _declare_cursor) — a multi-second DECLARE must
            # not stall every concurrent writer
            return self._declare_cursor(stmt)
        # autocommit single-table appends take the SHARED write mode plus
        # a per-table lock: appenders to DIFFERENT tables stage and commit
        # concurrently (per-table delta manifests make the commit path
        # contention-free across tables), while structural statements
        # below still drain them through the exclusive mode
        if isinstance(stmt, (A.InsertStmt, A.CopyStmt)) \
                and not (self.dtm.current is not None
                         and self.dtm.current.state == "active"):
            with self._write_lock.shared(), \
                    (self._table_lock(stmt.table)
                     if self._append_needs_table_lock(stmt.table)
                     else _NullSlot()):
                if isinstance(stmt, A.InsertStmt):
                    out = self._insert(stmt)
                else:
                    out = self._copy(stmt)
                self._post_commit()
                return out
        # every other statement mutates shared state (catalog, manifest,
        # dictionaries, settings, tx) — one writer at a time per process
        with self._write_lock:
            return self._execute_write(stmt)

    def _table_lock(self, table: str):
        """Per-table append serializer (same-table appenders queue; the
        base storage table keys the lock so partition children share their
        parent's)."""

        base = table.split("#", 1)[0]
        with self._table_locks_mu:
            lk = self._table_locks.get(base)
            if lk is None:
                lk = self._table_locks[base] = threading.RLock()
            return lk

    def _append_needs_table_lock(self, table: str) -> bool:
        """Whether same-table appenders must still queue on the per-table
        serializer. With write intents on, N appenders stage disjoint
        segment deltas and resolve at commit with zero claim retries —
        UNLESS the table has a dict-encoded TEXT column: Dictionary.encode
        grows shared code maps, and divergent codes assigned by truly
        concurrent appenders are only reconciled by the legacy CAS path's
        conflict, so those tables keep the serializer."""
        if not getattr(self.settings, "write_intents_enabled", True):
            return True
        try:
            schema = self.catalog.get(table.split("#", 1)[0])
        except Exception:
            return True
        return any(c.type.kind is T.Kind.TEXT and c.encoding != "raw"
                   for c in schema.columns)

    def _execute_write(self, stmt):
        if isinstance(stmt, A.CreateTableStmt):
            return self._create_table(stmt)
        if isinstance(stmt, A.AlterTableStmt):
            return self._alter_table(stmt)
        if isinstance(stmt, A.DropTableStmt):
            existed = stmt.name in self.catalog
            schema0 = self.catalog.get(stmt.name) if existed else None
            self.catalog.drop_table(stmt.name, stmt.if_exists)
            if existed:
                # all storage tables backing this relation (partitions are
                # child storage tables named <parent>#<part>)
                storage = schema0.storage_tables()
                # invalidate open cursors that scanned this table: their
                # deferred shards may still dereference the table's files
                # (raw TEXT blobs, dictionaries) at RETRIEVE time
                for cname, batch in list(self._cursors.items()):
                    spec = getattr(getattr(batch, "comp", None),
                                   "input_spec", ())
                    if any(t == stmt.name or t in storage
                           for t, *_ in spec):
                        self._cursors[cname] = (
                            f'cursor "{cname}" was invalidated by DROP '
                            f'TABLE {stmt.name}')
                self._drop_log.append(stmt.name)
                # drop storage too: manifest commit removes the table's
                # segfiles from visibility; data dir cleanup is best-effort
                tx = self.store.manifest.begin()
                touched = False
                for st in storage:
                    if st in tx["tables"]:
                        del tx["tables"][st]
                        touched = True
                if touched:
                    self.store.manifest.commit_tx(tx)
                    # the dead delta chains go NOW (we hold the exclusive
                    # write mode): a same-named CREATE restarts at seq 1
                    # and must not collide with stale claims
                    for st in storage:
                        self.store.manifest.drop_table_deltas(st)
                self.store._invalidate_dicts(stmt.name)
                # compiled programs scanning this table must not survive a
                # same-named recreate (the shape signature could coincide)
                self.executor.invalidate_table(stmt.name)

                for st in storage:
                    shutil.rmtree(os.path.join(self.path, "data", st),
                                  ignore_errors=True)
            return "DROP TABLE"
        if isinstance(stmt, A.InsertStmt):
            out = self._insert(stmt)
            self._post_commit()
            return out
        if isinstance(stmt, A.CopyStmt):
            out = self._copy(stmt)
            self._post_commit()
            return out
        if isinstance(stmt, A.DeleteStmt):
            out = self._delete(stmt)
            self._post_commit()
            return out
        if isinstance(stmt, A.UpdateStmt):
            out = self._update(stmt)
            self._post_commit()
            return out
        if isinstance(stmt, A.CreateExternalTableStmt):
            return self._create_external_table(stmt)
        if isinstance(stmt, A.AnalyzeStmt):
            return self._analyze(stmt.table)
        if isinstance(stmt, A.CreateIndexStmt):
            return self._create_index(stmt)
        if isinstance(stmt, A.DropIndexStmt):
            return self._drop_index(stmt)
        if isinstance(stmt, A.CreateExtensionStmt):
            return self._create_extension(stmt)
        if isinstance(stmt, A.CloseCursorStmt):
            if stmt.cursor not in self._cursors:
                raise ValueError(f'cursor "{stmt.cursor}" does not exist')
            del self._cursors[stmt.cursor]
            self._cursor_owner.pop(stmt.cursor, None)
            return "CLOSE CURSOR"
        if isinstance(stmt, A.ShowStmt):
            if stmt.what == "resource_group":
                return self.resgroups.current_group()
            return str(self.settings.show(stmt.what))
        if isinstance(stmt, A.SetStmt):
            if stmt.name == "resource_group":
                # per-THREAD binding (one server connection = one thread),
                # like SET ROLE picking the backend's resgroup
                self.resgroups.set_group(str(stmt.value))
                return "SET"
            self.settings.set(stmt.name, stmt.value)
            if stmt.name.startswith("resource_"):
                # wake blocked waiters: a lowered/disabled cap must admit
                # them now, not at their timeout
                self.resgroups.kick()
            if stmt.name in ("optimizer", "plan_cache_params",
                             "scalar_device_enabled", "cost_feedback"):
                # planner selection / literal-hoisting / scalar-lowering
                # changed: cached bound plans were produced under the
                # other regime. motion_pipeline_buckets needs no clear:
                # binding never reads it — the executor's program cache
                # keys on codegen_settings_sig and recompiles
                self._select_cache.clear()
            if stmt.name in ("motion_pipeline", "motion_pipeline_buckets"):
                from greengage_tpu.planner import cost as _cost
                _cost.set_motion_overlap(self._motion_overlap_factor())
            return "SET"
        if isinstance(stmt, A.ResourceGroupStmt):
            return self._resource_group(stmt)
        if isinstance(stmt, A.TxStmt):
            if stmt.action == "begin":
                self.dtm.begin()
                return "BEGIN"
            if stmt.action == "commit":
                written = set(getattr(self.dtm.current, "tables_written", ()))
                self.dtm.commit()
                self._post_commit()
                # a committed raw-table republish GC's the old blobs —
                # only NOW do open cursors over those tables go stale
                for t in written:
                    if self.store.has_raw_columns(t):
                        self._tombstone_raw_cursors(t)
                return "COMMIT"
            self.dtm.abort()
            return "ROLLBACK"
        raise SqlError(f"unsupported statement {type(stmt).__name__}")

    # ------------------------------------------------------------------
    def _create_index(self, stmt: A.CreateIndexStmt) -> str:
        """CREATE INDEX (pg_index analog): registers the index and builds
        the per-segfile block-value sidecars eagerly so the first probe
        doesn't pay the build. 'btree' and 'bitmap' both lower to the
        block-value index (see TableStore.block_index)."""
        if stmt.using not in ("btree", "bitmap"):
            raise SqlError(f"unknown index access method {stmt.using!r}")
        for schema in (self.catalog.get(t) for t in self.catalog.tables):
            if stmt.name in schema.indexes:
                if stmt.if_not_exists:
                    return "CREATE INDEX"
                raise SqlError(f'index "{stmt.name}" already exists')
        schema = self.catalog.get(stmt.table)
        if self._external_def(schema) is not None:
            raise SqlError("cannot index an external table")
        col = schema.column(stmt.column)
        if col.type.kind is T.Kind.TEXT and col.encoding == "raw":
            raise SqlError(
                "raw-encoded text cannot be indexed (block indexes probe "
                "storage values; raw storage has no per-row value column)")
        schema.indexes[stmt.name] = {"column": stmt.column,
                                     "using": stmt.using}
        self.catalog._save()
        self._build_index_sidecars(schema)
        self._select_cache.clear()
        # staged-input cache entries predate the index (same manifest
        # version): drop them so the next scan actually prunes
        getattr(self.executor, "_stage_cache", {}).clear()
        return "CREATE INDEX"

    def _build_index_sidecars(self, schema) -> None:
        snap = self.store.manifest.snapshot()
        for storage in schema.storage_tables():
            tmeta = snap["tables"].get(storage)
            if not tmeta:
                continue
            cols = {d["column"] for d in schema.indexes.values()}
            for segkey, files in tmeta["segfiles"].items():
                base = os.path.join(
                    self.store.data_root(int(segkey)), storage)
                for rel in files:
                    fn = os.path.basename(rel)
                    parts = fn.split(".")
                    if len(parts) == 3 and fn.endswith(".ggb") \
                            and parts[0] in cols:
                        self.store.block_index(base, rel, table=storage)

    def _drop_index(self, stmt: A.DropIndexStmt) -> str:
        for schema in (self.catalog.get(t) for t in self.catalog.tables):
            if stmt.name in schema.indexes:
                del schema.indexes[stmt.name]
                self.catalog._save()
                self._select_cache.clear()
                return "DROP INDEX"
        if stmt.if_exists:
            return "DROP INDEX"
        raise SqlError(f'index "{stmt.name}" does not exist')

    def _create_extension(self, stmt) -> str:
        """Import the extension module (registering its UDFs) and record
        it in the catalog so reopened clusters and workers reload it
        (reference: src/backend/commands/extension.c:1546)."""
        from greengage_tpu import extensions as X

        if stmt.name in self.catalog.extensions:
            if stmt.if_not_exists:
                return "CREATE EXTENSION"
            raise ValueError(f'extension "{stmt.name}" already exists')
        X.load(stmt.name, cluster_path=self.path)
        self.catalog.extensions.append(stmt.name)
        self.catalog._save()
        return "CREATE EXTENSION"

    def _analyze(self, table: str | None) -> str:
        """ANALYZE [table]: collect per-column NDV/min-max/null-frac/MCV
        into the catalog (pg_statistic analog; planner/stats.py)."""
        from greengage_tpu.planner.stats import analyze_table

        names = [table] if table else list(self.catalog.tables)
        snap = self.store.manifest.snapshot()
        for n in names:
            schema = self.catalog.get(n)
            if self._external_def(schema) is not None:
                if table:
                    raise SqlError("cannot ANALYZE an external table")
                continue   # database-wide ANALYZE skips externals
            schema.stats = analyze_table(self.store, schema, snap)
        self.catalog._save()
        self._select_cache.clear()   # fresh stats can change plans
        return "ANALYZE"

    # ------------------------------------------------------------------
    def _post_commit(self) -> None:
        """Synchronous mirror replication after a committed write (the
        syncrep gate analog): mirrors are copied up to the new manifest
        version before the statement returns, so FTS can always promote.
        SET mirror_sync = off trades that away; mirrors then go stale and
        refresh_sync_state() blocks their promotion."""
        if self.dtm.current is not None and getattr(self.dtm.current, "state", "") == "active":
            return   # still invisible; replicate/archive at COMMIT
        with self._pc_lock:
            self._post_commit_locked()

    def _post_commit_locked(self) -> None:
        if self.settings.archive_mode and self.settings.archive_dir:
            # continuous archiving: ship the committed version before the
            # statement returns (archive_command semantics); a failing
            # archive logs but never fails the write
            from greengage_tpu.storage.archive import Archive

            try:
                Archive(self.settings.archive_dir).archive_now(
                    self.path, self.store)
            except Exception as e:
                self.log.error("archive", f"archiving failed: {e}")
        # standby master (gpinitstandby): ship the committed tail; a
        # failing sync logs, counts, and widens the lag gauge — but never
        # fails the write (async-standby semantics). The liveness beat is
        # stamped either way so the watcher distinguishes "primary alive
        # but shipping fails" (lag grows, no promotion) from "primary
        # silent" (promotion after standby_promote_deadline_s).
        from greengage_tpu.runtime import standby as _standby

        sb = _standby.registered_standby(self.path)
        if sb is not None:
            try:
                _standby.sync(self.path, sb)
            except Exception as e:
                self.log.error("standby", f"standby sync failed: {e}")
                _standby.note_sync_failure(self.path)
            _standby.primary_beat(self.path,
                                  self.catalog.segments.version)
            self.fts.start()    # idempotent: idle-cadence beat coverage
        if self.replicator is None:
            return
        if self.settings.mirror_sync:
            self.replicator.sync()
        else:
            self.replicator.refresh_sync_state()
        # persist the topology only when sync state / roles actually moved
        # (a full catalog save per INSERT would rewrite every table's stats)
        segs = self.catalog.segments
        sig = (segs.version, tuple(e.mode_synced for e in segs.entries))
        if sig != getattr(self, "_cfg_sig", None):
            self._cfg_sig = sig
            self.catalog._save()

    # ------------------------------------------------------------------
    # ---- WITH RECURSIVE (nodeRecursiveunion.c / WorkTableScan role) ----
    def _select_recursive(self, stmt, rctes: dict) -> Result:
        """Session-level fixpoint iteration: materialize each recursive
        CTE by running the base term, then re-running the recursive term
        against a worktable of the previous iteration's NEW rows until
        none appear. Every term executes as an ordinary distributed
        statement; accumulation tables are real (ephemeral) tables, so
        the final query plans/distributes normally. UNION (not ALL)
        dedupes rows across iterations — which is also the cycle guard."""

        MAX_ITER = 500
        mapping: dict[str, str] = {}
        created: list[str] = []
        # unique scratch names: concurrent statements — including OTHER
        # PROCESSES sharing this cluster directory — must never collide
        uid = f"{os.getpid():x}_{next(_REC_COUNTER)}"
        try:
            for name, rc in rctes.items():
                acc = f"__rec_{uid}_{name}"
                wtbl = f"__recw_{uid}_{name}"
                base = _rename_base_tables(_copy.deepcopy(rc.base), mapping)
                # bind once for exact output types (constant-only base
                # terms skip the binder and infer from the result), then
                # execute
                outs0 = None
                try:
                    _, outs0 = Binder(
                        self.catalog, self.store,
                        subquery_executor=self._scalar_subquery,
                        optimizer=self.settings.optimizer).bind_select(
                            _copy.deepcopy(base))
                except SqlError:
                    pass      # constant-only base: infer from the result
                r = self._execute(base)
                if outs0 is None:
                    outs0 = [_inferred_col(nm, np.asarray(r.cols[cid]))
                             for nm, cid in zip(r.columns, r._order)]
                coldefs = ", ".join(
                    f"{c.name} {_ddl_type(c.type)}" for c in outs0)
                for t in (acc, wtbl):
                    self.sql(f"drop table if exists {t}")
                    self.sql(f"create table {t} ({coldefs}) "
                             "distributed randomly")
                    created.append(t)
                rows = r.rows()
                seen = set(rows) if not rc.union_all else None
                if seen is not None:
                    rows = list(seen)
                self._load_rows(acc, outs0, rows)
                cur = rows
                it = 0
                while cur:
                    it += 1
                    if it > MAX_ITER:
                        raise QueryError(
                            f'recursive CTE "{name}" exceeded {MAX_ITER} '
                            "iterations (cycle? use UNION instead of "
                            "UNION ALL, or add a bound)")
                    self.sql(f"delete from {wtbl}")
                    self._load_rows(wtbl, outs0, cur)
                    rec = _rename_base_tables(
                        _copy.deepcopy(rc.rec), {**mapping, name: wtbl})
                    nr = self._execute(rec).rows()
                    if seen is not None:
                        fresh = []
                        for t in nr:
                            if t not in seen:
                                seen.add(t)
                                fresh.append(t)
                        nr = fresh
                    if nr:
                        self._load_rows(acc, outs0, nr)
                    cur = nr
                mapping[name] = acc
            final = _rename_base_tables(_copy.deepcopy(stmt), mapping)
            if hasattr(final, "_recursive_ctes"):
                del final._recursive_ctes
            return self._execute(final)
        finally:
            for t in created:
                try:
                    self.sql(f"drop table if exists {t}")
                except Exception:
                    pass

    def _load_rows(self, table: str, outs, rows: list) -> None:
        """Host row tuples -> bulk column load matching ``outs`` types
        (DECIMAL results arrive descaled as float64 and reload as double
        precision — see _ddl_type)."""
        cols: dict = {}
        valids: dict = {}
        epoch = np.datetime64("1970-01-01")
        for i, c in enumerate(outs):
            vals = [r[i] for r in rows]
            mask = np.array([v is not None for v in vals], bool)
            kind = c.type.kind
            if kind is T.Kind.TEXT:
                cols[c.name] = ["" if v is None else str(v) for v in vals]
            elif kind in (T.Kind.FLOAT64, T.Kind.DECIMAL):
                cols[c.name] = np.array(
                    [0.0 if v is None else float(v) for v in vals],
                    np.float64)
            elif kind is T.Kind.DATE:
                cols[c.name] = np.array(
                    [0 if v is None else
                     int((np.datetime64(v, "D") - epoch)
                         .astype("timedelta64[D]").astype(np.int64))
                     for v in vals], np.int32)
            else:
                cols[c.name] = np.array(
                    [0 if v is None else int(v) for v in vals],
                    c.type.np_dtype)
            valids[c.name] = None if mask.all() else mask
        if rows:
            self.load_table(table, cols, valids)

    def _plan(self, stmt, force_multi_join: bool = False, info: dict | None = None):
        binder = Binder(self.catalog, self.store,
                        subquery_executor=self._scalar_subquery,
                        optimizer=self.settings.optimizer,
                        scalar_device=self.settings.scalar_device_enabled)
        with _trace.span("bind", cat="plan"):
            logical, outs = binder.bind_select(stmt)
        planned = plan_query(logical, self.catalog, self.store, self.numsegments,
                             force_multi_join=force_multi_join,
                             feedback=(self.feedback if bool(getattr(
                                 self.settings, "cost_feedback", True))
                                 else None))
        if self.settings.plan_validate:
            # checkPlan-before-dispatch (analysis/plancheck.py): a plan
            # violating a Motion/locality/prune invariant dies HERE with a
            # typed node path, never as a wrong answer after dispatch
            validate_plan(planned, self.catalog)
        if info is not None:
            info["memo_used"] = binder.memo_used
        # content digest of the LUT pool, computed once per bind: part of
        # the executor's executable-reuse shape signature (the compiled
        # program bakes these arrays)

        h = hashlib.sha1()
        for k in sorted(binder.consts):
            a = np.asarray(binder.consts[k])
            h.update(k.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        binder.consts["@consts_digest@"] = h.hexdigest()[:16]
        return planned, binder.consts, outs

    def _const_select(self, stmt: A.SelectStmt) -> Result:
        """FROM-less SELECT: one constant row evaluated on the host (the
        coordinator-only Result node analog — no dispatch, no mesh;
        reference: SELECT without FROM planning to a Result plan in
        src/backend/optimizer/plan/planner.c)."""
        from greengage_tpu.sql.binder import Binder, Scope, _ast_name

        if stmt.group_by or stmt.having or stmt.distinct or stmt.order_by:
            raise SqlError(
                "SELECT without FROM supports only a constant target list")
        import jax.numpy as jnp

        from greengage_tpu.ops.batch import Batch
        from greengage_tpu.ops.expr_eval import Evaluator

        binder = Binder(self.catalog, self.store,
                        subquery_executor=self._scalar_subquery)
        scope = Scope()
        one_row = Batch({"__one__": jnp.zeros((1,), jnp.int32)}, {}, None)
        where_false = False
        if stmt.where is not None:
            pred = binder._predicate(stmt.where, scope)
            keep = Evaluator(one_row, binder.consts).predicate(pred)
            where_false = not bool(np.asarray(keep)[0])
        cols, valids, names, order = {}, {}, [], []
        for i, it in enumerate(stmt.items):
            if isinstance(it.expr, A.Star):
                raise SqlError("SELECT * requires FROM")
            e = binder._expr(it.expr, scope)
            name = it.alias or _ast_name(it.expr)
            cid = f"c#{i}"
            t = e.type
            if isinstance(e, E.Literal) and e.value is None:
                val, valid_np = np.array([None], dtype=object), \
                    np.array([False])
            elif t.kind is T.Kind.TEXT:
                if not isinstance(e, E.Literal):
                    raise SqlError("SELECT without FROM supports only "
                                   "constant text expressions")
                val, valid_np = np.array([e.value], dtype=object), None
            else:
                ev = Evaluator(one_row, binder.consts)
                arr, valid = ev.value(e)
                arr = np.asarray(arr)
                valid_np = (None if valid is None
                            else np.asarray(valid).astype(bool))
                if valid_np is not None and not valid_np[0]:
                    val = np.array([None], dtype=object)
                elif t.kind is T.Kind.DECIMAL:
                    val, valid_np = arr / (10.0 ** t.scale), None
                elif t.kind is T.Kind.DATE:
                    val = (np.datetime64("1970-01-01", "D")
                           + arr.astype("timedelta64[D]"))
                    valid_np = None
                else:
                    val, valid_np = arr, None
            cols[cid] = val
            valids[cid] = valid_np
            names.append(name)
            order.append(cid)
        limit = stmt.limit if stmt.limit is not None else 1
        if limit == 0 or stmt.offset or where_false:
            cols = {k: v[:0] for k, v in cols.items()}
            valids = {k: (None if v is None else v[:0])
                      for k, v in valids.items()}
        return Result(columns=names, cols=cols, valids=valids, _order=order)

    def _scalar_subquery(self, stmt):
        """Run an uncorrelated scalar subquery at bind time (InitPlan
        analog): the value is inlined as a literal into the outer plan."""
        planned, consts, outs = self._plan(stmt)
        if len(outs) != 1:
            raise SqlError("scalar subquery must return one column")
        aux, dirty = self._load_external_aux(planned)
        if dirty:
            planned, consts, outs = self._plan(stmt)
        res = self.executor.run(planned, consts, outs,
                                aux_tables=aux or None)
        if len(res) > 1:
            raise SqlError("more than one row returned by a scalar subquery")
        t = outs[0].type
        if len(res) == 0:
            return None, t
        v = res.cols[outs[0].id][0]
        valid = res.valids.get(outs[0].id)
        if valid is not None and not valid[0]:
            return None, t
        # convert the presentation value back to storage representation
        if t.kind is T.Kind.DECIMAL:
            return T.decimal_to_int(float(v), t.scale), t
        if t.kind is T.Kind.DATE:
            return int((np.datetime64(v, "D")
                        - np.datetime64("1970-01-01", "D")).astype(int)), t
        if t.kind is T.Kind.TEXT:
            return str(v), T.TEXT
        if t.kind is T.Kind.FLOAT64:
            return float(v), t
        if t.kind is T.Kind.BOOL:
            return bool(v), t
        return int(v), t

    # ---- parallel retrieve cursors (endpoint/cdbendpoint.c analog) -----
    def _declare_cursor(self, stmt) -> str:
        """DECLARE <c> PARALLEL RETRIEVE CURSOR FOR <select>: run the mesh
        program once, keep every segment's output shard addressable as an
        ENDPOINT; RETRIEVE drains one endpoint without gathering the rest
        (reference: src/backend/cdb/endpoint/cdbendpoint.c — there results
        park on the segments behind direct connections, here as per-shard
        host buffers after the single device fetch)."""

        self._validate_declare(stmt)
        with self._write_lock:
            drop_mark = self._drop_base + len(self._drop_log)
            self._inflight_declares += 1
        try:
            # same plan/program memoization as _select: a drain-then-
            # redeclare workload must not replan + recompile each DECLARE
            planned, consts, outs, exec_key = self._cached_plan(stmt.query)
            aux, dirty = self._load_external_aux(planned)
            if dirty:
                planned, consts, outs, exec_key = self._cached_plan(
                    stmt.query)
            with (self._admission() if self.multihost is None
                  else _NullSlot()):
                try:
                    batch = self.executor.run(planned, consts, outs,
                                              cache_key=exec_key,
                                              deferred=True,
                                              aux_tables=aux or None)
                except QueryError as e:
                    if "duplicate keys" not in str(e):
                        raise
                    # same re-plan fallback as _select: the uniqueness
                    # heuristic was wrong at runtime -> CSR multi-match join
                    planned, consts, outs, exec_key = self._cached_plan(
                        stmt.query, force_multi_join=True)
                    batch = self.executor.run(planned, consts, outs,
                                              cache_key=exec_key,
                                              deferred=True,
                                              aux_tables=aux or None)
            with self._write_lock:
                prev = self._cursors.get(stmt.name)
                if prev is not None and not isinstance(prev, str):
                    # raced with another DECLARE of the same name
                    raise ValueError(f'cursor "{stmt.name}" already exists')
                # a table dropped while the (unlocked) run was in flight:
                # register the tombstone DROP TABLE could not place yet
                dropped = set(self._drop_log[drop_mark - self._drop_base:])
                hit = [t for t, *_ in batch.comp.input_spec if t in dropped]
                if hit:
                    self._cursors[stmt.name] = (
                        f'cursor "{stmt.name}" was invalidated by DROP '
                        f'TABLE {hit[0]}')
                    self._cursor_owner[stmt.name] = threading.get_ident()
                    return "DECLARE CURSOR (invalidated by concurrent DROP)"
                self._cursors[stmt.name] = batch
                # cursors are session-scoped (one server connection = one
                # thread); the server closes a dropped connection's cursors
                self._cursor_owner[stmt.name] = threading.get_ident()
            return f"DECLARE CURSOR ({batch.nendpoints} endpoints)"
        finally:
            with self._write_lock:
                self._inflight_declares -= 1
                if self._inflight_declares == 0 and self._drop_log:
                    # no mark can reference the log anymore: prune it
                    self._drop_base += len(self._drop_log)
                    self._drop_log.clear()

    def close_thread_cursors(self) -> None:
        """Release cursors declared by the calling thread (connection
        teardown; the reference's endpoints die with their session)."""

        me = threading.get_ident()
        with self._write_lock:
            for name in [n for n, t in self._cursor_owner.items() if t == me]:
                self._cursors.pop(name, None)
                self._cursor_owner.pop(name, None)

    def _validate_declare(self, stmt) -> None:
        """Host-side DECLARE checks; in multi-host mode these MUST run on
        the coordinator BEFORE the broadcast (workers enter the query's
        collectives unconditionally)."""
        existing = self._cursors.get(stmt.name)
        if existing is not None and not isinstance(existing, str):
            # (a str is a DROP TABLE tombstone — the name is reusable)
            raise ValueError(f'cursor "{stmt.name}" already exists')
        q = stmt.query
        if getattr(q, "order_by", None) or getattr(q, "limit", None) is not None \
                or getattr(q, "offset", 0):
            raise SqlError(
                "parallel retrieve cursors return per-endpoint streams; "
                "a cross-segment ORDER BY/LIMIT/OFFSET would need the "
                "gather this cursor exists to avoid")

    def _retrieve(self, stmt) -> Result:
        batch = self._cursors.get(stmt.cursor)
        if batch is None:
            raise ValueError(f'cursor "{stmt.cursor}" does not exist')
        if isinstance(batch, str):   # DROP TABLE tombstone
            raise ValueError(batch)
        if not 0 <= stmt.endpoint < batch.nendpoints:
            raise ValueError(
                f"endpoint {stmt.endpoint} out of range "
                f"(cursor has {batch.nendpoints})")
        try:
            return self.executor.finalize_endpoint(batch, stmt.endpoint)
        except (FileNotFoundError, OSError):
            # a DROP TABLE can delete this cursor's backing storage while
            # the (lock-free) decode is in flight; surface the tombstone
            # it planted instead of a raw IO error
            now = self._cursors.get(stmt.cursor)
            if isinstance(now, str):
                raise ValueError(now) from None
            raise

    def endpoints(self, cursor: str) -> list[dict]:
        """gp_endpoints analog: addressable endpoints of an open cursor."""
        batch = self._cursors.get(cursor)
        if batch is None:
            raise ValueError(f'cursor "{cursor}" does not exist')
        if isinstance(batch, str):
            raise ValueError(batch)
        return [{"cursor": cursor, "endpoint": k,
                 "state": "READY"} for k in range(batch.nendpoints)]

    @property
    def _plan_cache_info(self) -> dict:
        """Last _cached_plan outcome, PER THREAD: concurrent statements on
        the threaded SQL server must not clobber each other's plan-cache
        reporting (Result.stats["plan_cache"], EXPLAIN ANALYZE)."""
        return getattr(self._pc_info_local, "info", {})

    @_plan_cache_info.setter
    def _plan_cache_info(self, info: dict) -> None:
        self._pc_info_local.info = info

    @staticmethod
    def _attach_params(consts, pv, ptypes, info):
        """Bind the statement's CURRENT hoisted values into a fresh consts
        dict (the cached plan's pool is shared across statements); shared
        tail of _cached_plan's hit and miss paths."""
        from greengage_tpu.sql.paramize import ParamVector

        consts = dict(consts)
        if ptypes is not None:
            consts["@params@"] = ParamVector(pv.values, ptypes)
            _counters.inc("params_hoisted", len(pv.values))
        else:
            info["params"] = 0
        return consts

    def _cached_plan(self, stmt, force_multi_join: bool = False):
        """Memoized planning for SELECT-shaped statements (plain SELECT
        and the DECLARE CURSOR body) — the plancache.c prepared-statement
        role. Plan-safe literals are hoisted into a parameter vector
        (sql/paramize.py) and the cache key becomes the literal-STRIPPED
        statement signature plus the hoisted literals' exact types:
        `WHERE x > 5` and `WHERE x > 6` share one bound plan and, through
        the executor's program cache, one XLA executable. Unsafe literals
        (partition keys, distribution-key equality, strings, LIMIT) stay
        pinned in the key so planning-relevant values never silently
        generalize. The key also carries the manifest version (bound
        plans embed dictionary codes/LUTs that grow with data); the
        executor's SHAPE signature decides executable reuse across
        versions. A force_multi_join re-plan is remembered under the
        PLAIN key so repeats skip the failing unique-join program. Real
        LRU, bounded by the plan_cache_size GUC.
        -> (planned, consts, outs, exec_key)."""
        from greengage_tpu.sql.paramize import ParamVector, paramize

        version = self.store.manifest.snapshot().get("version", 0)
        with _trace.span("paramize", cat="plan"):
            norm, pv, sig = (paramize(stmt, self.catalog)
                             if self.settings.plan_cache_params
                             else (stmt, None, None))
        if sig is not None and sig in self._paramize_fallback:
            # this shape is known-unparameterizable: plan value-pinned
            # directly instead of re-paying the doomed normalized bind
            # for every new literal combination
            norm, pv, sig = stmt, None, None
        info = {"hit": False, "params": 0, "fallback": False}
        self._plan_cache_info = info
        if pv is not None:
            info["params"] = len(pv.values)
        key_sig = sig if sig is not None else repr(stmt)
        # the calibration version joins the key: a feedback promotion
        # touching this shape's digests bumps it, so a re-calibrated
        # shape re-plans instead of serving the stale bound plan
        fbv = self.feedback.version_for(key_sig)
        key = (key_sig, version, fbv)
        cache = self._select_cache
        if not force_multi_join:
            hit = cache.get(key)
            if hit is None and sig is not None:
                # this shape previously fell back to a value-pinned plan
                # (binder cannot parameterize it): look it up under the
                # full repr so the fallback is paid once, not per call
                fbk = (repr(stmt), version,
                       self.feedback.version_for(repr(stmt)))
                fb = cache.get(fbk)
                if fb is not None and fb[4] is None:
                    key, hit = fbk, fb
            if hit is not None:
                try:
                    cache.move_to_end(key)
                except KeyError:
                    pass   # concurrent statement evicted it; `hit` is ours
                _counters.inc("plan_cache_hit")
                info["hit"] = True
                planned, consts, outs, ek, ptypes = hit
                self._pc_info_local.planned = planned   # slow-log digest
                return planned, self._attach_params(consts, pv, ptypes,
                                                    info), outs, ek
        _counters.inc("plan_cache_miss")
        ptypes = pv.types if (pv is not None and norm is not stmt) else None
        try:
            with _trace.span("plan", cat="plan"):
                planned, consts, outs = self._plan(
                    norm, force_multi_join=force_multi_join)
        except (SqlError, NotImplementedError, TypeError):
            if ptypes is None:
                raise
            # a shape the binder cannot parameterize (raw-text predicates,
            # exotic coercions): pin every value and re-plan classically —
            # a genuine user error surfaces identically from the re-plan.
            # Memoize the signature so later literal variants of the shape
            # skip the doomed normalized bind entirely
            _counters.inc("plan_cache_fallback")
            info.update(fallback=True, params=0)
            if len(self._paramize_fallback) > 1024:
                self._paramize_fallback.clear()
            self._paramize_fallback.add(key_sig)
            ptypes = None
            key_sig = repr(stmt)
            key = (key_sig, version, self.feedback.version_for(key_sig))
            with _trace.span("plan", cat="plan", fallback=True):
                planned, consts, outs = self._plan(
                    stmt, force_multi_join=force_multi_join)
        ek = key_sig + ("#multi" if force_multi_join else "")
        # register the shape -> digest dependency set so a promotion on
        # any digest this plan uses bumps version_for(key_sig)
        self.feedback.note_shape(key_sig, planned)
        cache[key] = (planned, consts, outs, ek, ptypes)
        try:
            cache.move_to_end(key)
        except KeyError:
            pass
        bound = max(int(getattr(self.settings, "plan_cache_size", 256)), 1)
        while len(cache) > bound:
            try:
                cache.popitem(last=False)
            except KeyError:   # concurrent statement emptied it
                break
        self._pc_info_local.planned = planned   # slow-log digest source
        return planned, self._attach_params(consts, pv, ptypes,
                                            info), outs, ek

    # ---- vectorized serving (exec/batchserve.py) ---------------------
    def _batcher(self):
        b = self._batch_server
        if b is None:
            with self._batch_server_mu:
                b = self._batch_server
                if b is None:
                    from greengage_tpu.exec.batchserve import BatchServer

                    b = self._batch_server = BatchServer(self)
        return b

    def _batch_eligible(self, consts, aux) -> bool:
        """May this SELECT ride the batched-serving path from _select?
        Parameterized single-host autocommit reads only: external-table
        loads stay serial, and a statement inside an open transaction
        must see its session's uncommitted state. A multihost
        COORDINATOR batches too, but enrolls in _coordinator_sql BEFORE
        the per-statement broadcast (_mh_batch_try) — by the time
        _select runs there, the statement is already inside a classic
        two-phase exchange the workers are parked in, so this gate stays
        False under multihost."""
        if not bool(getattr(self.settings, "batch_serving_enabled", False)):
            return False
        if _overload.CONTROLLER.brownout_active():
            # brownout: stacked member params multiply device footprints
            # exactly when HBM has no headroom — serve serially until
            # pressure clears (docs/ROBUSTNESS.md "Overload protection")
            return False
        if self.multihost is not None or aux:
            return False
        if (consts or {}).get("@params@") is None:
            return False
        cur = self.dtm.current
        return cur is None or cur.state != "active"

    def _select(self, stmt: A.SelectStmt) -> Result:
        rctes = getattr(stmt, "_recursive_ctes", None)
        if rctes:
            return self._select_recursive(stmt, rctes)
        if isinstance(stmt, A.SelectStmt) and not stmt.from_:
            # pre-screen BEFORE attempting the host fast path: a bind-time
            # failure after an InitPlan scalar subquery already executed
            # would re-run that subquery on the device-path retry
            fastpath = (not stmt.group_by and not stmt.having
                        and not stmt.distinct and not stmt.order_by
                        and not any(_contains_agg(it.expr)
                                    for it in stmt.items)
                        and not any(isinstance(it.expr, A.Star)
                                    for it in stmt.items))
            if fastpath:
                try:
                    return self._const_select(stmt)
                except SqlError:
                    pass   # residual host-path rejections (non-constant
                    # text exprs, stat aggregates the screen can't see)
                    # fall through to the ConstRel device path; the
                    # screen above keeps InitPlan subqueries from running
                    # twice for the COMMON fallthrough shapes
        planned, consts, outs, exec_key = self._cached_plan(stmt)
        pc_info = self._plan_cache_info
        # external tables materialize to host arrays before execution
        # (fileam external_beginscan role); first-seen strings grow the
        # dictionary, so the bound plan refreshes afterwards
        aux, dirty = self._load_external_aux(planned)
        if dirty:
            planned, consts, outs, exec_key = self._cached_plan(stmt)
            pc_info = self._plan_cache_info
        # resource-queue admission (ResLockPortal analog): bound concurrent
        # mesh statements; excess statements queue or time out. Multi-host
        # admission happens on the COORDINATOR before the broadcast (a
        # post-broadcast wait here would strand workers in the collectives)
        with (self._admission() if self.multihost is None
              else _NullSlot()):
            if self._batch_eligible(consts, aux):
                # vectorized serving: enroll in the admission window for
                # this statement shape — one XLA dispatch serves every
                # in-flight member. None = the batch fell back (or this
                # member should run alone): continue on the classic path
                res = self._batcher().submit(planned, consts, outs,
                                             exec_key, consts["@params@"])
                if res is not None:
                    if isinstance(res.stats, dict):
                        res.stats["plan_cache"] = dict(pc_info)
                    self._record_stats(res, planned, exec_key)
                    return res
            try:
                # executor adds the manifest version; the bare statement
                # identity lets it evict compiled programs of old versions
                res = self.executor.run(planned, consts, outs,
                                        cache_key=exec_key,
                                        aux_tables=aux or None)
                if isinstance(res.stats, dict):
                    res.stats["plan_cache"] = dict(pc_info)
                self._record_stats(res, planned, exec_key)
                return res
            except QueryError as e:
                if "duplicate keys" not in str(e):
                    raise
                # the uniqueness heuristic was wrong at runtime: re-plan with
                # the CSR multi-match join forced everywhere; cached under
                # the plain key so repeats skip the failing program
                planned, consts, outs, exec_key = self._cached_plan(
                    stmt, force_multi_join=True)
                res = self.executor.run(planned, consts, outs,
                                        cache_key=exec_key,
                                        aux_tables=aux or None)
                if isinstance(res.stats, dict):
                    res.stats["plan_cache"] = dict(self._plan_cache_info)
                self._record_stats(res, planned, exec_key)
                return res

    def _record_stats(self, res, planned=None, exec_key=None) -> None:
        self.stat_activity.append({
            "ts": time.time(),
            "wall_ms": res.wall_ms,
            "rows": len(res),
            **(res.stats or {}),
        })
        if len(self.stat_activity) > 200:
            del self.stat_activity[0]
        if planned is not None and exec_key is not None:
            self._feedback_reconcile(planned, exec_key, res)

    def _feedback_reconcile(self, planned, exec_key: str, res) -> None:
        """Close the measurement loop after one execution: per-node
        actual rows (always-on filter counters + instrumented runs) and
        the exact ``rows_out`` reconcile against the planner's
        ``est_rows`` per structural digest; the AOT-measured executable
        bytes reconcile against ``est_bytes`` per shape. Coordinator /
        single-host only: workers adopt the coordinator's applied
        scales from the statement broadcast instead (identical inputs
        would yield identical updates, but the asymmetric rows_out of a
        gathered result must not desync lockstep planning)."""
        if not bool(getattr(self.settings, "cost_feedback", True)):
            return
        if self.multihost is not None and not self.multihost.is_coordinator:
            return
        stats = res.stats if isinstance(res.stats, dict) else {}
        if stats.get("batched"):
            # batched members share one program; per-member node
            # attribution is masked at demux — skip (the classic runs
            # of the shape feed the loop)
            return
        key_sig = exec_key[:-6] if exec_key.endswith("#multi") else exec_key
        mem = stats.get("mem") or {}
        measured = mem.get("measured") or {}
        measured_total = (measured.get("temp_bytes", 0)
                          + measured.get("argument_bytes", 0)
                          + measured.get("output_bytes", 0)) or None
        self.feedback.reconcile(
            key_sig, planned, len(res), stats.get("node_rows"),
            measured_bytes=measured_total,
            est_bytes=mem.get("est_bytes"))

    def _explain(self, stmt: A.ExplainStmt):
        if not isinstance(stmt.query, (A.SelectStmt, A.UnionStmt)):
            raise SqlError("EXPLAIN supports SELECT only")
        text = ""
        if not stmt.analyze:
            info: dict = {}
            planned, consts, outs = self._plan(stmt.query, info=info)
            # report the planner that actually produced the join order (the
            # memo bails without stats / on >10 rels / explicit JOIN syntax)
            text = ("Optimizer: %s\n" % (
                "memo (Cascades-lite)" if info.get("memo_used")
                else "fallback (left-deep DP/greedy)")) + describe(planned)
        if stmt.analyze:
            # ANALYZE goes through the plan cache (plancache exercise +
            # reporting); the instrumented program itself never enters the
            # executor's program cache, so compile_ms below is a real
            # fresh-compile measurement
            planned, consts, outs, _ek = self._cached_plan(stmt.query)
            pc_info = dict(self._plan_cache_info)
            aux, dirty = self._load_external_aux(planned)
            if dirty:
                planned, consts, outs, _ek = self._cached_plan(stmt.query)
                pc_info = dict(self._plan_cache_info)
            # per-node instrumentation (explain_gp.c's Instrumentation
            # tree analog): every operator reports its actual output rows;
            # device time is attributed per node proportional to its rows
            # (one fused XLA program has no per-op clocks — the
            # host-attributed split Theseus-style accounting needs), and
            # Motion nodes additionally report the bytes they moved
            res = self.executor.run(planned, consts, outs, instrument=True,
                                    aux_tables=aux or None)
            # instrumented runs carry actual rows for EVERY operator —
            # the richest feedback the loop gets (joins/aggregates that
            # normal runs only observe at the root)
            self._feedback_reconcile(planned, _ek, res)
            s = res.stats or {}
            annot = self._analyze_annotations(planned, s)
            text = describe(planned, annot=annot)
            text += (f"\n Plan cache: {'hit' if pc_info.get('hit') else 'miss'}"
                     f"{' (fallback: unparameterizable shape)' if pc_info.get('fallback') else ''}"
                     f", {pc_info.get('params', 0)} params hoisted, "
                     f"compile {s.get('compile_ms', 0)} ms")
            text += (
                f"\n Execution time: {res.wall_ms:.2f} ms, rows: {len(res)}"
                f"\n Segments: {s.get('segments')}, capacity tiers used: "
                f"{s.get('tiers_used')}, result capacity/segment: "
                f"{s.get('below_gather_capacity')}"
                f"\n Tables scanned: {', '.join(s.get('scan_tables', []))}")
            if s.get("stage_ms") is not None:
                # host-data-path breakdown (docs/PERF.md): where the wall
                # time went — host staging vs device program vs fetch
                text += (f"\n Host data path: staging {s['stage_ms']:.2f} ms"
                         f", device compute {s['compute_ms']:.2f} ms, "
                         f"result fetch {s['fetch_ms']:.2f} ms")
            io = s.get("scan_io") or {}
            if io:
                text += (f"\n Scan I/O: {io.get('scan_files_read', 0)} files"
                         f" read, {io.get('scan_bytes_decoded', 0)} bytes "
                         f"decoded, block cache "
                         f"{io.get('scan_cache_hit', 0)} hit / "
                         f"{io.get('scan_cache_miss', 0)} miss / "
                         f"{io.get('scan_cache_evict', 0)} evicted")
            mline = self._memory_line(s.get("mem"))
            if mline:
                text += "\n " + mline
            if s.get("fused_kernel"):
                text += "\n Fused dense-agg pallas kernel: yes"
            for t, (kept, total) in (s.get("zone_prune") or {}).items():
                text += f"\n Zone-map prune {t}: {kept}/{total} blocks"
            for t, (kept, total) in (s.get("dynamic_prune") or {}).items():
                text += (f"\n Dynamic partition selector {t}: "
                         f"{kept}/{total} children staged")
            if s.get("spill_passes"):
                text += f"\n Spill passes: {s['spill_passes']}"
            for k, v in (s.get("metrics") or {}).items():
                if not k.startswith("nrows_"):
                    text += f"\n {k}: {v}"
        r = Result(columns=["QUERY PLAN"],
                   cols={"p": np.array(text.split("\n"), dtype=object)},
                   valids={}, _order=["p"])
        r.plan_text = text
        return r

    @staticmethod
    def _memory_line(mem: dict | None) -> str | None:
        """The statement-level EXPLAIN ANALYZE Memory line: the vmem
        admission estimate alongside the MEASURED executable bytes (XLA
        memory_analysis — args/temps/output) and, where the backend
        reports one, the live device peak (docs/OBSERVABILITY.md
        "Memory accounting")."""
        if not mem:
            return None
        line = (f"Memory: vmem estimate "
                f"{mem.get('est_bytes', 0) / 1e6:.1f} MB/segment")
        meas = mem.get("measured")
        if meas:
            total = (meas.get("argument_bytes", 0)
                     + meas.get("temp_bytes", 0)
                     + meas.get("output_bytes", 0))
            line += (f"; executable measured: "
                     f"args {meas.get('argument_bytes', 0) / 1e6:.1f}"
                     f" + temps {meas.get('temp_bytes', 0) / 1e6:.1f}"
                     f" + out {meas.get('output_bytes', 0) / 1e6:.1f}"
                     f" = {total / 1e6:.1f} MB")
        if mem.get("admitted_by") == "measured":
            line += " (admitted by measured bytes)"
        if mem.get("device_peak_bytes_in_use") is not None:
            line += (f"; device peak "
                     f"{mem['device_peak_bytes_in_use'] / 1e6:.1f} MB")
        return line

    @staticmethod
    def _analyze_annotations(planned, s: dict) -> dict:
        """Per-plan-node EXPLAIN ANALYZE annotations: actual rows out,
        host-attributed device ms (the whole program is one fused XLA
        dispatch, so compute_ms splits proportional to each node's rows —
        exact per-segment clocks would need per-op program breaks), and
        moved bytes for Motion nodes (rows x output row width). Keys are
        id(plan-node), matching describe()'s annot contract."""
        from greengage_tpu.planner.logical import Motion as _Motion

        node_rows = s.get("node_rows") or {}
        if not node_rows:
            return {}
        node_mem = s.get("node_est_bytes") or {}
        id2node = {}
        stack = [planned]
        while stack:
            p = stack.pop()
            id2node[id(p)] = p
            stack.extend(p.children)
        total = sum(node_rows.values())
        compute = float(s.get("compute_ms") or 0.0)
        annot = {}
        for pid, n in node_rows.items():
            parts = [f"actual rows={n}"]
            if total > 0 and compute > 0:
                parts.append(f"device ~{compute * n / total:.2f} ms "
                             f"(host-attributed)")
            node = id2node.get(pid)
            if isinstance(node, _Motion):
                try:
                    width = sum(int(c.type.np_dtype.itemsize)
                                for c in node.out_cols())
                except Exception:
                    width = 8
                parts.append(f"motion ~{n * width} B")
            # per-node Memory: this node's slice of the compiled device
            # estimate (capacity x widths; spill merges keep the last
            # merge program's slices — pass clones don't re-map)
            mb = node_mem.get(pid)
            if mb:
                parts.append(f"memory ~{mb >> 10} KB")
            annot[pid] = ", ".join(parts)
        return annot

    # ------------------------------------------------------------------
    def _create_table(self, stmt: A.CreateTableStmt):
        cols = [
            Column(c.name, type_from_name(c.type_name, c.typmod), not c.not_null)
            for c in stmt.columns
        ]
        kind = {"hash": PolicyKind.HASH, "random": PolicyKind.RANDOM,
                "replicated": PolicyKind.REPLICATED}[stmt.dist_kind]
        policy = DistPolicy(kind, tuple(stmt.dist_keys) if kind is PolicyKind.HASH else (),
                            self.numsegments)
        options = dict(stmt.options)
        options.setdefault("compresstype", self.settings.default_compresstype)
        options.setdefault("compresslevel", self.settings.default_compresslevel)
        schema = TableSchema(stmt.name, cols, policy, options)
        if stmt.partition_kind is not None:
            if stmt.partition_col not in [c.name for c in cols]:
                raise SqlError(
                    f"partition column {stmt.partition_col} is not a column")
            pcol = schema.column(stmt.partition_col)
            if pcol.type.kind is T.Kind.TEXT:
                raise SqlError("TEXT partition keys are not supported")
            if policy.kind is PolicyKind.REPLICATED:
                # GP parity: replicated tables cannot be partitioned
                raise SqlError("DISTRIBUTED REPLICATED tables cannot be "
                               "partitioned")
            schema.partition_by = (stmt.partition_kind, stmt.partition_col)
            parts: list[Partition] = []
            for pd in stmt.partition_defs:
                parts.extend(self._build_partitions(pd, pcol,
                                                    stmt.partition_kind))
            self._validate_partitions(parts, stmt.partition_kind, stmt.name)
            schema.partitions = parts
        self.catalog.create_table(schema, stmt.if_not_exists)
        return "CREATE TABLE"

    def _part_literal(self, node, col):
        """Coerce a partition-bound literal into the column's storage
        representation (dates = epoch days, decimals = scaled ints).
        NULL bounds are meaningless (NULL keys route to the DEFAULT
        partition) and rejected."""
        binder = Binder(self.catalog, self.store)
        lit = binder._expr(node, _EmptyScope())
        if not isinstance(lit, E.Literal):
            raise SqlError("partition bounds must be literals")
        lit = binder._coerce_literal(lit, col.type)
        if lit.value is None:
            raise SqlError("partition bounds/values cannot be NULL")
        return lit.value

    def _build_partitions(self, pd, pcol, kind) -> list[Partition]:
        if pd.default:
            return [Partition(pd.name, default=True)]
        if kind == "list":
            if not pd.values:
                raise SqlError(
                    f"partition {pd.name}: LIST partitions need VALUES")
            if pd.lo is not None or pd.hi is not None or pd.every is not None:
                raise SqlError(
                    f"partition {pd.name}: START/END/EVERY are RANGE syntax")
            vals = tuple(self._part_literal(v, pcol) for v in pd.values)
            return [Partition(pd.name, values=vals)]
        if pd.values:
            raise SqlError(
                f"partition {pd.name}: VALUES is LIST syntax; this table "
                "is partitioned BY RANGE")
        lo = self._part_literal(pd.lo, pcol) if pd.lo is not None else None
        hi = self._part_literal(pd.hi, pcol) if pd.hi is not None else None
        if pd.every is None:
            return [Partition(pd.name, lo=lo, hi=hi)]
        if lo is None or hi is None:
            raise SqlError("EVERY requires both START and END")
        # the step is a DELTA in the column's storage units (days for
        # DATE, scaled units for DECIMAL), not a value of the column type
        binder = Binder(self.catalog, self.store)
        step_lit = binder._expr(pd.every, _EmptyScope())
        if not isinstance(step_lit, E.Literal) \
                or not isinstance(step_lit.value, (int, float)):
            raise SqlError("EVERY step must be a numeric literal "
                           "(storage units: days for DATE)")
        step = int(step_lit.value) if isinstance(lo, int) else step_lit.value
        if not step or step <= 0:
            raise SqlError("EVERY step must be positive")
        out, k, cur = [], 1, lo
        while cur < hi:
            nxt = min(cur + step, hi)
            out.append(Partition(f"{pd.name}_{k}", lo=cur, hi=nxt))
            cur, k = nxt, k + 1
        return out

    @staticmethod
    def _validate_partitions(parts, kind, table) -> None:
        names = [p.name for p in parts]
        if len(set(names)) != len(names):
            raise SqlError(f"duplicate partition name in {table}")
        if sum(1 for p in parts if p.default) > 1:
            raise SqlError("multiple DEFAULT partitions")
        real = [p for p in parts if not p.default]
        if kind == "range":
            bounded = sorted(
                (p for p in real),
                key=lambda p: (p.lo is not None,
                               p.lo if p.lo is not None else 0))
            for a, b in zip(bounded, bounded[1:]):
                a_hi = a.hi
                b_lo = b.lo
                if a_hi is None or b_lo is None or b_lo < a_hi:
                    raise SqlError(
                        f"overlapping range partitions {a.name}/{b.name}")
        else:
            seen: set = set()
            for p in real:
                for v in p.values:
                    if v in seen:
                        raise SqlError(
                            f"value {v!r} in multiple list partitions")
                    seen.add(v)
        if not parts:
            raise SqlError("partitioned table needs at least one partition")

    def _admission(self):
        """Statement admission: resource-group slot (weighted backoff when
        the global cap binds) nested inside/with the legacy resource
        queue; either is a no-op when unconfigured. The wait is metered
        into the queue_wait_ms histogram (`gg metrics`) and the
        statement's trace."""
        t0 = time.monotonic()
        st = ExitStack()
        try:
            with _trace.span("admission", cat="queue"):
                st.enter_context(self.resgroups.admit())
                st.enter_context(self.resqueue.admit())
        except BaseException:
            # a queue timeout after the group slot was granted must release
            # the slot (and unpin the thread's group memory ceiling)
            st.close()
            raise
        finally:
            _histograms.observe("queue_wait_ms",
                                (time.monotonic() - t0) * 1e3)
        return st

    def resgroup_status(self) -> list[dict]:
        """gp_toolkit.gp_resgroup_status analog."""
        return self.resgroups.status()

    def _resource_group(self, stmt) -> str:
        allowed = {"concurrency", "memory_limit_mb", "cpu_weight"}
        bad = set(stmt.options) - allowed
        if bad:
            raise SqlError(f"unknown resource group option(s): "
                           f"{', '.join(sorted(bad))}")
        if stmt.action == "create":
            self.resgroups.create(stmt.name, **stmt.options)
            tag = "CREATE RESOURCE GROUP"
        elif stmt.action == "drop":
            self.resgroups.drop(stmt.name)
            tag = "DROP RESOURCE GROUP"
        else:
            self.resgroups.alter(stmt.name, **stmt.options)
            tag = "ALTER RESOURCE GROUP"
        # persist definitions (built-ins included so tuned caps survive)
        self.catalog.resource_groups = [
            g.to_dict() for g in self.resgroups.groups.values()]
        self.catalog._save()
        return tag

    # ---- external tables (fileam.c / CREATE EXTERNAL TABLE role) ------
    def _create_external_table(self, stmt) -> str:
        """An external table is a catalog-only relation whose rows come
        from (or go to) a URL/command at scan/insert time — no manifest
        storage (reference: src/backend/access/external/fileam.c,
        exttablecmds.c). Readable scans re-read the source every query."""
        cols = []
        for c in stmt.columns:
            col = Column(c.name, type_from_name(c.type_name, c.typmod),
                         not c.not_null)
            if col.type.kind is T.Kind.TEXT:
                # external TEXT is dictionary-coded at load (the scan path
                # stages device arrays; raw byte blobs need storage files)
                col = Column(col.name, col.type, col.nullable,
                             encoding="dict")
            cols.append(col)
        if not stmt.urls and stmt.exec_cmd is None:
            raise SqlError("external table needs LOCATION or EXECUTE")
        schema = TableSchema(
            stmt.name, cols,
            DistPolicy(PolicyKind.RANDOM, (), self.numsegments),
            {"external": {
                "writable": stmt.writable,
                "urls": list(stmt.urls),
                "exec_cmd": stmt.exec_cmd,
                "format": dict(stmt.format_opts),
                "reject_limit": stmt.reject_limit,
            }})
        self.catalog.create_table(schema, stmt.if_not_exists)
        return "CREATE EXTERNAL TABLE"

    @staticmethod
    def _external_def(schema) -> dict | None:
        return schema.options.get("external")

    def _external_chunks(self, schema, ext: dict) -> list:
        """Fetch the raw bytes of an external source as
        (blob, starts_new_file) pairs — HEADER must be stripped once per
        FILE, not once per scan (a gpfdist stream is one file split into
        chunks; a glob/EXECUTE yields one file per chunk)."""
        from greengage_tpu.runtime import ingest

        chunks: list = []
        if ext["exec_cmd"] is not None:
            # EXECUTE ON ALL: the command runs once per segment with
            # GP_SEGMENT_ID/GP_SEGMENT_COUNT env (fileam.c EXECUTE popen)

            for seg in range(self.numsegments):
                env = dict(os.environ,
                           GP_SEGMENT_ID=str(seg),
                           GP_SEGMENT_COUNT=str(self.numsegments))
                out = subprocess.run(
                    ext["exec_cmd"], shell=True, env=env,
                    capture_output=True, timeout=120)
                if out.returncode != 0:
                    raise SqlError(
                        f"external EXECUTE failed on segment {seg}: "
                        f"{out.stderr.decode(errors='replace')[:200]}")
                chunks.append((out.stdout, True))
            return chunks

        for url in ext["urls"]:
            if url.startswith("gpfdist://"):
                for ci, blob in enumerate(
                        ingest.fetch_chunks(url, self.numsegments)):
                    chunks.append((blob, ci == 0))
            elif url.startswith("s3://"):
                # object store (gpcloud role): one external file per object
                from greengage_tpu.runtime import s3

                objs = s3.fetch(url)
                if not objs:
                    raise SqlError(f"external location {url!r} matches "
                                   "no objects")
                for _key, blob in objs:
                    chunks.append((blob, True))
            else:
                path = url[len("file://"):] if url.startswith("file://") else url
                matches = sorted(_glob.glob(path))
                if not matches:
                    raise SqlError(f"external location {url!r} matches "
                                   "no files")
                for m in matches:
                    with open(m, "rb") as f:
                        chunks.append((f.read(), True))
        return chunks

    def _load_external_aux(self, planned) -> dict:
        """Materialize every external table scanned by this plan into host
        arrays for aux staging (the external_beginscan role: re-read per
        query, SREH reject limits applied)."""
        from greengage_tpu.planner.logical import Scan
        from greengage_tpu.runtime import ingest

        aux: dict = {}
        any_dirty = False
        stack = [planned]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            if not isinstance(node, Scan) or node.table in aux:
                continue
            schema = self.catalog.get(node.table) \
                if node.table in self.catalog else None
            ext = self._external_def(schema) if schema is not None else None
            if ext is None:
                continue
            if ext["writable"]:
                raise SqlError(
                    f'"{node.table}" is a WRITABLE external table; it '
                    "cannot be scanned")
            fmt = ext.get("format", {})
            delim = fmt.get("delimiter", ",")
            header = str(fmt.get("header", "false")).lower() in ("true", "1")
            null_s = fmt.get("null", "")
            cols_all = {c.name: [] for c in schema.columns}
            valids_all = {c.name: [] for c in schema.columns}
            rejects: list = []
            line_base = 0
            for blob, file_start in self._external_chunks(schema, ext):
                text = blob.decode("utf-8", errors="replace")
                cols, valids, rej = ingest.parse_csv_rows(
                    text, schema, delim, header and file_start, null_s,
                    line_base=line_base)
                for n in cols_all:
                    cols_all[n].extend(cols[n])
                    valids_all[n].extend(valids[n])
                rejects.extend(rej)
                line_base += blob.count(b"\n")
            limit = ext.get("reject_limit")
            if rejects and limit is None:
                line, _raw, err = rejects[0]
                raise SqlError(f"external table {node.table} line {line}: "
                               f"{err}")
            if limit is not None and len(rejects) > limit:
                raise SqlError(
                    f"external scan aborted: {len(rejects)} rejected rows "
                    f"exceed SEGMENT REJECT LIMIT {limit}")
            if rejects:
                ingest.append_error_log(self.path, node.table, rejects)
            enc_c: dict = {}
            enc_v: dict = {}
            dict_dirty = False
            for c in schema.columns:
                va = np.array(valids_all[c.name], dtype=bool)
                if c.type.kind is T.Kind.TEXT:
                    d = self.store.dictionary(node.table, c.name)
                    strs = ["" if not ok else s for s, ok
                            in zip(cols_all[c.name], va)]
                    before = len(d)
                    enc_c[c.name] = d.encode(strs)
                    dict_dirty = dict_dirty or len(d) != before
                else:
                    enc_c[c.name] = np.array(cols_all[c.name],
                                             dtype=c.type.np_dtype)
                enc_v[c.name] = None if va.all() else va
            if dict_dirty:
                self.store.flush_dicts(node.table)
                # new codes can shift LUT-dependent bound plans: the
                # caller re-binds against the grown dictionary
                self._select_cache.clear()
                any_dirty = True
            aux[node.table] = (enc_c, enc_v)
        return aux, any_dirty

    def _alter_table(self, stmt: A.AlterTableStmt) -> str:
        """ALTER TABLE ... ADD/DROP PARTITION (reference: cdbpartition.c
        partition maintenance). DROP is O(1): unlink the child storage
        table; no other partition moves."""
        schema = self.catalog.get(stmt.table)
        if not schema.is_partitioned:
            raise SqlError(f'table "{stmt.table}" is not partitioned')
        kind, pcol_name = schema.partition_by
        if stmt.action == "add_partition":
            pcol = schema.column(pcol_name)
            new = self._build_partitions(stmt.partition, pcol, kind)
            self._validate_partitions(schema.partitions + new, kind,
                                      stmt.table)
            schema.partitions.extend(new)
            self.catalog._save()
            self._select_cache.clear()
            return "ALTER TABLE"
        # drop_partition
        part = schema.partition(stmt.partition_name)   # KeyError -> msg
        child = part.storage_name(stmt.table)
        if len(schema.partitions) == 1:
            raise SqlError("cannot drop the last partition; DROP TABLE")
        schema.partitions = [p for p in schema.partitions
                             if p.name != part.name]
        self.catalog._save()
        for cname, batch in list(self._cursors.items()):
            spec = getattr(getattr(batch, "comp", None), "input_spec", ())
            if any(t == stmt.table for t, *_ in spec):
                self._cursors[cname] = (
                    f'cursor "{cname}" was invalidated by DROP PARTITION '
                    f'on {stmt.table}')
        # same in-flight-DECLARE race as DROP TABLE: a cursor still being
        # declared over this table must tombstone itself at registration
        self._drop_log.append(stmt.table)
        tx = self.store.manifest.begin()
        if child in tx["tables"]:
            del tx["tables"][child]
            self.store.manifest.commit_tx(tx)
            self.store.manifest.drop_table_deltas(child)

        shutil.rmtree(os.path.join(self.path, "data", child),
                      ignore_errors=True)
        self._select_cache.clear()
        self.executor.invalidate_table(stmt.table)
        self._post_commit()
        return "ALTER TABLE"

    def _insert(self, stmt: A.InsertStmt):
        schema = self.catalog.get(stmt.table)
        ext = self._external_def(schema)
        if stmt.query is not None:
            return self._insert_select(schema, ext, stmt)
        if ext is not None:
            raise SqlError(
                f'"{stmt.table}" is an external table; load it via its '
                "LOCATION source (INSERT ... SELECT writes WRITABLE "
                "external tables)")
        names = stmt.columns or schema.column_names
        if set(names) != set(schema.column_names):
            raise SqlError("INSERT must provide all columns")
        cols: dict[str, list] = {n: [] for n in names}
        valids: dict[str, list] = {n: [] for n in names}
        binder = Binder(self.catalog, self.store)
        scope = _EmptyScope()
        for row in stmt.rows:
            if len(row) != len(names):
                raise SqlError("INSERT row arity mismatch")
            for n, v in zip(names, row):
                col = schema.column(n)
                lit = binder._expr(v, scope)
                if not isinstance(lit, E.Literal):
                    raise SqlError("INSERT values must be literals")
                lit = binder._coerce_literal(lit, col.type)
                if lit.value is None:
                    valids[n].append(False)
                    cols[n].append(_zero_for(col.type))
                else:
                    valids[n].append(True)
                    cols[n].append(lit.value)
        enc_cols = {}
        enc_valids = {}
        for n in names:
            col = schema.column(n)
            if col.type.kind is T.Kind.TEXT:
                enc_cols[n] = cols[n]
            else:
                enc_cols[n] = np.array(cols[n], dtype=col.type.np_dtype)
            va = np.array(valids[n], dtype=bool)
            if not va.all():
                enc_valids[n] = va
        n = self._write_rows(stmt.table, enc_cols, enc_valids)
        return f"INSERT 0 {n}"

    def _insert_select(self, schema, ext, stmt) -> str:
        """INSERT INTO t SELECT ...: run the query, convert the presented
        values back to storage representation, and either append to the
        table or — for WRITABLE EXTERNAL tables — emit CSV to the
        location/command (the gpfdist WET/EXECUTE writer role)."""
        res = self._select(stmt.query) if not isinstance(stmt.query, A.UnionStmt) \
            else self._execute(stmt.query)
        names = stmt.columns or schema.column_names
        if set(names) != set(schema.column_names):
            raise SqlError("INSERT must provide all columns")
        if len(res.columns) != len(names):
            raise SqlError(
                f"INSERT SELECT arity mismatch: query returns "
                f"{len(res.columns)} columns, target has {len(names)}")
        if ext is not None:
            if not ext["writable"]:
                raise SqlError(
                    f'cannot write to READABLE external table "{schema.name}"')
            return self._write_external(schema, ext, res)
        cols: dict = {}
        valids: dict = {}
        order = res._order
        for n, oid in zip(names, order):
            c = schema.column(n)
            data = res.cols[oid]
            v = res.valids.get(oid)
            if c.type.kind is T.Kind.DECIMAL:
                # presented value is a float; re-scale with round-half-
                # away (the engine's numeric rounding rule)
                f = np.asarray(data, dtype=np.float64) * (10.0 ** c.type.scale)
                data = (np.floor(np.abs(f) + 0.5) * np.sign(f)).astype(np.int64)
            elif c.type.kind is T.Kind.DATE:
                data = (np.asarray(data, dtype="datetime64[D]")
                        - np.datetime64("1970-01-01", "D")).astype(np.int32)
            elif c.type.kind is T.Kind.TEXT:
                data = ["" if s is None else str(s) for s in data]
            else:
                data = np.asarray(data)
                if v is not None:
                    # NULL slots may carry NaN/garbage; zero them so the
                    # dtype cast cannot fail
                    data = np.where(v, data, 0)
                data = data.astype(c.type.np_dtype)
            cols[n] = data
            if v is not None:
                valids[n] = np.asarray(v, dtype=bool)
        n = self._write_rows(schema.name, cols, valids)
        self._post_commit()
        return f"INSERT 0 {n}"

    def _write_external(self, schema, ext, res) -> str:
        buf = io.StringIO()
        fmt = ext.get("format", {})
        w = _csv.writer(buf, delimiter=fmt.get("delimiter", ","))
        null_s = fmt.get("null", "")
        for row in res.rows():
            w.writerow([null_s if v is None else v for v in row])
        payload = buf.getvalue()
        if ext["exec_cmd"] is not None:
            out = subprocess.run(ext["exec_cmd"], shell=True,
                                 input=payload.encode(), timeout=120,
                                 capture_output=True)
            if out.returncode != 0:
                raise SqlError(
                    "external EXECUTE writer failed: "
                    f"{out.stderr.decode(errors='replace')[:200]}")
            return f"INSERT 0 {len(res)}"
        url = ext["urls"][0]
        if url.startswith("gpfdist://"):
            raise SqlError("writing through a gpfdist URL is not supported; "
                           "use file://, s3://, or EXECUTE")
        if url.startswith("s3://"):
            # one object per INSERT batch (the gpcloud writable layout:
            # unique keys so parallel writers never clobber)

            from greengage_tpu.runtime import s3

            key = s3.store(url, f"gg_{_uuid.uuid4().hex[:12]}.csv",
                           payload.encode())
            self.log.info("external", f"wrote s3 object {key}")
            return f"INSERT 0 {len(res)}"
        path = url[len("file://"):] if url.startswith("file://") else url
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(payload)
        return f"INSERT 0 {len(res)}"

    def _write_rows(self, table: str, columns, valids) -> int:
        """All write paths (INSERT/COPY/load_table) stage into the open
        transaction if one is active; published at COMMIT. (Reads inside the
        tx still see the committed snapshot only.) Partitioned tables route
        rows to their partitions' child storage tables here."""
        schema = self.catalog.get(table)
        if schema.is_partitioned and "#" not in table:
            return self._write_routed(schema, columns, valids or {})
        tx = self.dtm.current
        if tx is not None and tx.state == "active":
            return tx.insert(table, columns, valids)
        return self.store.insert(table, columns, valids)

    def _write_routed(self, schema, columns, valids) -> int:
        """Split a row batch by partition and write each slice into its
        child storage table (one manifest commit when inside a tx; one per
        child otherwise — each child insert is atomic either way)."""
        # whole-batch validation BEFORE any child stages: a later child's
        # constraint failure must not leave earlier slices in the user's tx
        for c in schema.columns:
            v = valids.get(c.name)
            if not c.nullable and v is not None and not np.all(v):
                raise SqlError(
                    f'null value in column "{c.name}" violates not-null '
                    "constraint")
        kind, pcol = schema.partition_by
        col = schema.column(pcol)
        raw = columns[pcol]
        if col.type.kind is T.Kind.DATE and not isinstance(raw, np.ndarray):
            vals = np.array([T.date_to_days(v) for v in raw], dtype=np.int32)
        elif col.type.kind is T.Kind.DECIMAL and not isinstance(raw, np.ndarray):
            vals = np.array([T.decimal_to_int(v, col.type.scale) for v in raw],
                            dtype=np.int64)
        else:
            vals = np.asarray(raw, dtype=col.type.np_dtype)
        pidx = np.asarray(schema.route_rows(vals, valids.get(pcol)))
        if (pidx < 0).any():
            bad = vals[pidx < 0][0]
            raise SqlError(
                f"no partition of {schema.name} accepts value {bad!r} "
                "(and there is no DEFAULT partition)")

        def _slice(v, m):
            if isinstance(v, T.Coded):
                return T.Coded(v.vocab, v.codes[m])
            if isinstance(v, np.ndarray):
                return v[m]
            return np.asarray(v, dtype=object)[m]

        # all children stage into ONE manifest tx (atomic multi-partition
        # insert), the user's own transaction when one is open
        total = 0
        with self._autocommit_tx() as tx:
            for i, p in enumerate(schema.partitions):
                m = pidx == i
                if not m.any():
                    continue
                sub_c = {k: _slice(v, m) for k, v in columns.items()}
                sub_v = {k: _slice(v, m) for k, v in valids.items()
                         if v is not None}
                total += tx.insert(p.storage_name(schema.name), sub_c, sub_v)
        return total

    @_contextmanager
    def _autocommit_tx(self):
        """Yield the thread's active transaction, or an ephemeral one that
        commits on success / aborts on error — the shared wrapper for
        writes that must land atomically across several storage tables."""
        tx = self.dtm.current
        if tx is not None and tx.state == "active":
            yield tx
            return
        own = self.dtm.begin()
        try:
            yield own
            self.dtm.commit()
        except Exception:
            if self.dtm.current is own:
                self.dtm.abort()
            raise

    def cluster_exec(self, cmd: str, timeout: float = 60.0) -> list[dict]:
        """gpssh analog: run a shell command on every host of the cluster
        — workers over the control channel, the coordinator locally.
        -> [{'host': id, 'ok': bool, 'output': str}]."""

        out = []
        local = subprocess.run(cmd, shell=True, capture_output=True,
                               timeout=timeout)
        out.append({"host": 0, "ok": local.returncode == 0,
                    "output": (local.stdout + local.stderr).decode(
                        errors="replace")[-2000:]})
        if self.multihost is not None and self.multihost.is_coordinator \
                and not getattr(self, "_mh_degraded", None):
            ch = self.multihost.channel
            try:
                with ch.exchange():
                    ch.send({"op": "exec", "cmd": cmd, "timeout": timeout})
                    # the ack deadline must outlive the command's own
                    # timeout, or a slow-but-healthy remote command would
                    # classify the worker as hung
                    acks = ch.collect_raw(deadline=float(timeout) + 30.0,
                                          phase="exec")
                for i, a in enumerate(acks):
                    out.append({"host": i + 1, "ok": bool(a.get("ok")),
                                "output": (a.get("error") or "")[:2000]})
            except Exception as e:
                out.append({"host": "?", "ok": False, "output": str(e)})
        return out

    def vacuum(self, table: str | None = None) -> dict:
        """Compact deletion bitmaps away (the lazy-VACUUM role for the
        visimap analog): every table carrying a bitmap is rewritten
        live-rows-only at its current width, which also restores zone-map
        pruned scans. -> {table: live rows kept}."""
        if self.dtm.current is not None and self.dtm.current.state == "active":
            raise SqlError("VACUUM cannot run inside a transaction")
        with self._write_lock:
            compacted: dict = {}
            snap = self.store.manifest.snapshot()
            for t, tmeta in snap.get("tables", {}).items():
                if table is not None and t != table:
                    continue
                if tmeta.get("delmask"):
                    n = self.store.rewrite_table(
                        t, self.catalog.get(t).policy.numsegments)
                    compacted[t] = n
            self.store.reap_gc()
            self._post_commit()
        return compacted

    def load_table(self, table: str, columns: dict, valids: dict | None = None):
        """Bulk load host arrays (the gpfdist/COPY fast path for benchmarks)."""
        n = self._write_rows(table, columns, valids)
        self._post_commit()
        return n

    def _copy(self, stmt: A.CopyStmt):
        schema = self.catalog.get(stmt.table)
        if self._external_def(schema) is not None:
            raise SqlError("COPY targets heap tables; external tables load "
                           "from their LOCATION at scan time")
        delim = stmt.options.get("delimiter", ",")
        header = str(stmt.options.get("header", "false")).lower() in ("true", "1")
        null_s = stmt.options.get("null", "")
        reject_limit = stmt.options.get("segment_reject_limit")
        reject_limit = int(reject_limit) if reject_limit is not None else None
        is_url = stmt.path.startswith("gpfdist://")

        if not is_url and reject_limit is None:
            # native fast path (fstream parsing analog); quoted files and
            # custom null markers fall back to the Python reader below
            from greengage_tpu.storage.csv_native import (CsvFallback,
                                                          parse_file)

            parsed_native = None
            try:
                parsed_native = parse_file(
                    stmt.path, schema, delim, header, null_s)
            except CsvFallback:
                pass
            except ValueError:
                # bad data: re-parse via the SREH-aware reader so the error
                # names the offending line (the try covers ONLY the parse —
                # a write-path error must not re-ingest the file)
                pass
            if parsed_native is not None:
                cols_n, valids_n = parsed_native
                n = self._write_rows(stmt.table, cols_n, valids_n)
                return f"COPY {n}"

        from greengage_tpu.runtime import ingest

        # chunk sources: gpfdist serves disjoint newline-aligned slices
        # fetched in parallel (the per-segment external scan role); local
        # files load as one chunk
        if is_url:
            nchunks = max(int(stmt.options.get("chunks", self.numsegments)), 1)
            chunks = ingest.fetch_chunks(stmt.path, nchunks)
        else:
            with open(stmt.path, "rb") as f:
                chunks = [f.read()]

        all_cols: dict[str, list] = {c.name: [] for c in schema.columns}
        all_valids: dict[str, list] = {c.name: [] for c in schema.columns}
        rejects: list = []
        line_base = 0
        for ci, blob in enumerate(chunks):
            try:
                text = blob.decode("utf-8")
            except UnicodeDecodeError:
                # invalid bytes: salvage per line; undecodable lines go to
                # the reject path instead of silently corrupting TEXT
                lines = []
                for li, raw in enumerate(blob.split(b"\n")):
                    try:
                        lines.append(raw.decode("utf-8"))
                    except UnicodeDecodeError:
                        rejects.append((line_base + li + 1, repr(raw),
                                        "invalid UTF-8"))
                        lines.append("")   # keep line numbering aligned
                text = "\n".join(lines)
            cols, valids, rej = ingest.parse_csv_rows(
                text, schema, delim, header and ci == 0, null_s,
                line_base=line_base)
            for name in all_cols:
                all_cols[name].extend(cols[name])
                all_valids[name].extend(valids[name])
            rejects.extend(rej)
            line_base += blob.count(b"\n")
        if rejects and reject_limit is None:
            line, raw, err = rejects[0]
            raise SqlError(f"COPY line {line}: {err}")
        if reject_limit is not None and len(rejects) > reject_limit:
            raise SqlError(
                f"COPY aborted: {len(rejects)} rejected rows exceed "
                f"SEGMENT REJECT LIMIT {reject_limit}")
        if rejects:
            ingest.append_error_log(self.path, stmt.table, rejects)

        enc_cols = {}
        enc_valids = {}
        for c in schema.columns:
            va = np.array(all_valids[c.name], dtype=bool)
            if c.type.kind is T.Kind.TEXT:
                enc_cols[c.name] = all_cols[c.name]
            else:
                enc_cols[c.name] = np.array(all_cols[c.name], dtype=c.type.np_dtype)
            if not va.all():
                enc_valids[c.name] = va
        n = self._write_rows(stmt.table, enc_cols, enc_valids)
        tag = f"COPY {n}"
        if rejects:
            tag += f" (rejected {len(rejects)} rows, logged)"
        return tag

    def error_log(self, table: str) -> list[dict]:
        """Rejected-row log for a table (gp_read_error_log analog)."""
        from greengage_tpu.runtime import ingest

        return ingest.read_error_log(self.path, table)

    # ------------------------------------------------------------------
    # DELETE / UPDATE: append-only storage rewrites the surviving rows and
    # republishes in one manifest commit (the visimap/SplitUpdate roles,
    # reference: src/backend/access/appendonly visimap + nodeSplitUpdate.c)
    # ------------------------------------------------------------------
    def _tx_for_dml(self, table: str, what: str):
        """DML inside a transaction stages a replacement built from the
        COMMITTED snapshot (tx reads see committed data only, like every
        read here), so a table already written in this tx cannot also be
        rewritten — the replacement would silently drop the tx's rows."""
        tx = self.dtm.current
        if tx is None or tx.state != "active":
            return None
        # partition children count as the parent (storage names "t#part")
        written = {t.split("#", 1)[0] for t in tx.tables_written}
        if table.split("#", 1)[0] in written:
            raise SqlError(
                f"{what}: table was already modified in this transaction "
                "(DML reads the committed snapshot; interleaved rewrite "
                "would lose the transaction's own writes)")
        return tx

    def _run_raw(self, sel_stmt):
        planned, consts, outs = self._plan(sel_stmt)
        res = self.executor.run(planned, consts, outs, raw=True)
        return res, outs

    def _check_dml_target(self, table: str):
        schema = self.catalog.get(table)
        if self._external_def(schema) is not None:
            raise SqlError(
                f'"{table}" is an external table; DML is not supported '
                "(reference: external tables reject UPDATE/DELETE)")

    def _check_no_raw_dml(self, table: str):
        self._check_dml_target(table)
        # raw DML republishes decoded strings (see _decode_raw_out); only
        # the partitioned+raw combination stays out — raw surrogates don't
        # identify the storage child the string lives in
        if self.store.has_raw_columns(table) \
                and self.catalog.get(table).is_partitioned:
            raise SqlError(
                f'table "{table}" is partitioned with raw-encoded TEXT '
                "columns; DELETE/UPDATE are not supported on that "
                "combination")

    def _decode_raw_out(self, table: str, cname: str, data, valid):
        """DML republish: raw-column device surrogates -> host strings."""
        data = np.asarray(data, np.int64)
        strs = np.empty(len(data), dtype=object)
        m = (np.ones(len(data), bool) if valid is None
             else np.asarray(valid, bool))
        strs[m] = self.store.fetch_raw(table, cname, data[m])
        return strs

    def _tombstone_raw_cursors(self, table: str) -> None:
        """A committed raw-table republish GC's the old blobs; any open
        cursor over the table would fetch_raw from deleted files — plant
        the same tombstone DROP TABLE uses."""
        for cname, batch in list(self._cursors.items()):
            spec = getattr(getattr(batch, "comp", None), "input_spec", ())
            if any(t == table for t, *_ in spec):
                self._cursors[cname] = (
                    f'cursor "{cname}" was invalidated by DELETE/UPDATE '
                    f'on raw-text table {table}')

    def _replace_table(self, schema, enc, valids, tx, raw_strs=None) -> None:
        """Republish a table's full contents. Partitioned tables route the
        surviving rows by partition key and replace EVERY child (a child
        that receives no rows becomes empty) — UPDATEs may move rows
        across partitions, unlike the reference's pre-7 restriction."""
        if not schema.is_partitioned:
            if tx is not None:
                # tombstoning waits for COMMIT (rollback keeps old blobs
                # live; see the TxStmt commit handler)
                tx.replace(schema.name, enc, valids, raw_strs)
            else:
                self.store.replace_contents(schema.name, enc, valids,
                                            raw_strs)
                if raw_strs:
                    self._tombstone_raw_cursors(schema.name)
            return
        if raw_strs:
            raise SqlError("partitioned raw-text republish is not supported")
        _kind, pcol = schema.partition_by
        pidx = np.asarray(schema.route_rows(enc[pcol], valids.get(pcol)))
        if (pidx < 0).any():
            bad = enc[pcol][pidx < 0][0]
            raise SqlError(
                f"no partition of {schema.name} accepts value {bad!r} "
                "(and there is no DEFAULT partition)")
        # atomic across children: autocommit wraps the multi-child rewrite
        # in ONE manifest commit — a reader must never see a row twice (or
        # zero times) while an UPDATE moves it between partitions
        if tx is not None:
            for i, p in enumerate(schema.partitions):
                m = pidx == i
                tx.replace(p.storage_name(schema.name),
                           {k: v[m] for k, v in enc.items()},
                           {k: v[m] for k, v in valids.items()})
            return
        with self._autocommit_tx() as atx:
            for i, p in enumerate(schema.partitions):
                m = pidx == i
                atx.replace(p.storage_name(schema.name),
                            {k: v[m] for k, v in enc.items()},
                            {k: v[m] for k, v in valids.items()})

    def _predicate_mask(self, table: str, where) -> np.ndarray:
        """Evaluate a DML predicate over every visible row on the mesh:
        -> bool mask in gather order (segment-major, storage row order —
        the plain projection preserves it; NULL predicate = False)."""
        sel = A.SelectStmt(items=[A.SelectItem(where, alias="__dml_pred")],
                           from_=[A.BaseTable(table)])
        res, outs = self._run_raw(sel)
        o = outs[0]
        val = np.asarray(res.cols[o.id]).astype(bool)
        v = res.valids.get(o.id)
        return val if v is None else (val & np.asarray(v, bool))

    def _visimap_masks(self, table: str, pred_mask: np.ndarray) -> dict:
        """Merge a predicate mask over VISIBLE rows into per-segment
        full-length deletion bitmaps (1 = deleted). Replicated tables
        evaluate one copy and stamp every segment with the same bitmap
        (copies share row order by construction)."""
        schema = self.catalog.get(table)
        snap = self.store.manifest.snapshot()
        replicated = schema.policy.kind is PolicyKind.REPLICATED
        nseg = schema.policy.numsegments
        full = self.store.segment_rowcounts(table, snap)
        masks: dict = {}
        off = 0
        for seg in ([0] if replicated else range(nseg)):
            keep = self.store.delmask_keep(table, seg, snap)
            live = int(keep.sum()) if keep is not None else full[seg]
            m = pred_mask[off: off + live]
            off += live
            if not m.any():
                continue
            newdel = (np.zeros(full[seg], np.uint8) if keep is None
                      else (~keep).astype(np.uint8))
            live_pos = (np.flatnonzero(keep) if keep is not None
                        else np.arange(full[seg]))
            newdel[live_pos[m]] = 1
            masks[seg] = newdel
        if off != len(pred_mask):
            raise RuntimeError(
                f"DML scan returned {len(pred_mask)} rows but storage "
                f"holds {off} visible rows — concurrent write raced the "
                "statement; retry")
        if replicated and masks:
            masks = {s: masks[0] for s in range(nseg)}
        return masks

    def _delete(self, stmt: A.DeleteStmt, worker_scan_only: bool = False):
        self._check_no_raw_dml(stmt.table)
        tx = self._tx_for_dml(stmt.table, "DELETE")
        _reject_dml_subqueries(stmt.where)
        schema = self.catalog.get(stmt.table)
        # VISIBLE rows (manifest counts minus deletion bitmaps): the
        # reported DELETE count must not re-count already-deleted rows
        total = sum(self.store.live_rowcounts(stmt.table))
        raw_names = self.store.raw_column_names(stmt.table)
        if stmt.where is None:
            if worker_scan_only:
                return "DELETE 0"   # truncate: no mesh scan on either side
            empty = {c.name: np.empty(
                0, dtype=(np.int64 if c.name in raw_names
                          else c.type.np_dtype)) for c in schema.columns}
            raw_strs = {n: np.empty(0, dtype=object) for n in raw_names}
            self._replace_table(schema, empty, {}, tx, raw_strs or None)
            return f"DELETE {total}"
        if not schema.is_partitioned:
            # visimap path (appendonly_visimap.c analog): publish a
            # deletion bitmap instead of rewriting the table — DELETE
            # stages only the predicate's columns and writes O(bitmap),
            # not O(table)
            mask = self._predicate_mask(stmt.table, stmt.where)
            if worker_scan_only:
                return "DELETE 0"   # lockstep scan only; coordinator publishes
            masks = self._visimap_masks(stmt.table, mask)
            if masks:
                if tx is not None:
                    tx.set_delmask(stmt.table, masks)
                else:
                    self.store.set_delmask(stmt.table, masks)
            return f"DELETE {int(mask.sum())}"
        # partitioned fallback: republish survivors (predicate false OR
        # NULL) — per-child bitmaps need per-child row spans, deferred
        survive = A.Bin("or", A.Unary("not", stmt.where), A.IsNullTest(stmt.where, False))
        sel = A.SelectStmt(items=[A.SelectItem(A.Star())],
                           from_=[A.BaseTable(stmt.table)], where=survive)
        res, outs = self._run_raw(sel)
        if worker_scan_only:
            return "DELETE 0"
        enc = {}
        valids = {}
        raw_strs = {}
        for c, o in zip(schema.columns, outs):
            v = res.valids.get(o.id)
            if c.name in raw_names:
                # decode surrogates while the old blobs are still live
                raw_strs[c.name] = self._decode_raw_out(
                    stmt.table, c.name, res.cols[o.id], v)
                enc[c.name] = np.zeros(len(res.cols[o.id]), np.int64)
            else:
                enc[c.name] = np.ascontiguousarray(res.cols[o.id],
                                                   dtype=c.type.np_dtype)
            if v is not None:
                valids[c.name] = v
        self._replace_table(schema, enc, valids, tx, raw_strs or None)
        return f"DELETE {total - len(res)}"

    def _update(self, stmt: A.UpdateStmt, worker_scan_only: bool = False):
        self._check_no_raw_dml(stmt.table)
        tx = self._tx_for_dml(stmt.table, "UPDATE")
        _reject_dml_subqueries(stmt.where)
        schema = self.catalog.get(stmt.table)
        seen = set()
        for cname, _ in stmt.sets:
            if cname not in schema.column_names:
                raise SqlError(f'column "{cname}" of relation '
                               f'"{stmt.table}" does not exist')
            if cname in seen:
                raise SqlError(f'multiple assignments to column "{cname}"')
            seen.add(cname)
        # one raw pass: all columns + new-value expressions + update flag.
        # Outputs are tracked POSITIONALLY (star cols, then one slot per
        # device-evaluated SET, then the flag) — user column names can never
        # collide with internals.
        items = [A.SelectItem(A.Star())]
        text_literals = {}
        device_slots: dict[str, int] = {}   # colname -> index into outs
        ncols = len(schema.columns)
        next_slot = ncols
        dict_dirty = False
        for cname, e in stmt.sets:
            col = schema.column(cname)
            if col.type.kind is T.Kind.TEXT and col.encoding == "raw":
                raise SqlError(
                    f'column "{cname}" is raw-encoded text; SET on raw '
                    "columns is not supported (raw columns pass through "
                    "UPDATE unchanged)")
            if col.type.kind is T.Kind.TEXT:
                if isinstance(e, A.Str):
                    code = self.store.dictionary(stmt.table, cname).encode([e.value])[0]
                    dict_dirty = True
                    text_literals[cname] = np.int32(code)
                    continue
                if isinstance(e, A.Null):
                    text_literals[cname] = None
                    continue
                items.append(A.SelectItem(e, alias=f"__new_{cname}"))
            else:
                tname, typmod = _sql_type_name(col.type)
                items.append(A.SelectItem(A.CastExpr(e, tname, typmod),
                                          alias=f"__new_{cname}"))
            device_slots[cname] = next_slot
            next_slot += 1
        if dict_dirty and not worker_scan_only:
            self.store.flush_dicts(stmt.table)
        flag = stmt.where if stmt.where is not None else A.Bool(True)
        items.append(A.SelectItem(flag, alias="__upd"))
        flag_slot = next_slot
        # visimap split (nodeSplitUpdate.c + appendonly_visimap.c): mark
        # the old row versions deleted in the bitmap and APPEND the new
        # versions — the matched-rows scan pushes the WHERE (pruning
        # applies), so an UPDATE touches O(matched + bitmap), not
        # O(table). Partitioned / whole-table UPDATEs keep the republish.
        visimap = not schema.is_partitioned and stmt.where is not None
        pred_mask = None
        if visimap:
            pred_mask = self._predicate_mask(stmt.table, stmt.where)
        sel = A.SelectStmt(items=items, from_=[A.BaseTable(stmt.table)],
                           where=stmt.where if visimap else None)
        res, outs = self._run_raw(sel)
        if worker_scan_only:
            return "UPDATE 0"   # multi-host worker: scan only, no publish
        fo = outs[flag_slot]
        fval = res.cols[fo.id].astype(bool)
        fv = res.valids.get(fo.id)
        mask = fval if fv is None else (fval & fv)   # NULL predicate -> no update
        enc, valids = {}, {}
        raw_strs = {}
        for c, o in zip(schema.columns, outs[:ncols]):
            if c.type.kind is T.Kind.TEXT and c.encoding == "raw":
                # pass-through: decode while old blobs are live, republish
                v = res.valids.get(o.id)
                raw_strs[c.name] = self._decode_raw_out(
                    stmt.table, c.name, res.cols[o.id], v)
                enc[c.name] = np.zeros(len(res.cols[o.id]), np.int64)
                if v is not None:
                    valids[c.name] = np.asarray(v, bool)
                continue
            old = np.ascontiguousarray(res.cols[o.id], dtype=c.type.np_dtype)
            oldv = res.valids.get(o.id)
            oldv = np.ones(len(old), bool) if oldv is None else oldv
            if c.name in text_literals:
                lit = text_literals[c.name]
                if lit is None:
                    new = old
                    newv = np.zeros(len(old), bool)
                else:
                    new = np.full(len(old), lit, dtype=np.int32)
                    newv = np.ones(len(old), bool)
            elif c.name in device_slots:
                no = outs[device_slots[c.name]]
                if (c.type.kind is T.Kind.TEXT and no.dict_ref is not None
                        and no.dict_ref != (stmt.table, c.name)):
                    raise SqlError(
                        "text UPDATE from a different dictionary is not supported")
                new = np.ascontiguousarray(res.cols[no.id], dtype=c.type.np_dtype)
                nv = res.valids.get(no.id)
                newv = np.ones(len(new), bool) if nv is None else nv
            else:
                new, newv = old, oldv
            merged = np.where(mask, new, old)
            mergedv = np.where(mask, newv, oldv)
            enc[c.name] = merged.astype(c.type.np_dtype)
            if not mergedv.all():
                valids[c.name] = mergedv
        if visimap:
            if len(res) != int(pred_mask.sum()):
                raise RuntimeError(
                    f"UPDATE matched-row scan returned {len(res)} rows but "
                    f"the predicate pass marked {int(pred_mask.sum())} — "
                    "concurrent write raced the statement; retry")
            masks = self._visimap_masks(stmt.table, pred_mask)
            with self._autocommit_tx() as atx:
                if masks:
                    atx.set_delmask(stmt.table, masks)
                if len(res):
                    atx.insert_encoded(stmt.table, enc, valids,
                                       raw_strs or None)
            return f"UPDATE {int(pred_mask.sum())}"
        self._replace_table(schema, enc, valids, tx, raw_strs or None)
        return f"UPDATE {int(mask.sum())}"

    # ------------------------------------------------------------------
    def expand(self, new_numsegments: int) -> dict:
        """gpexpand analog: widen the cluster and redistribute every table.

        Phase 1 adds segments to the topology; phase 2 rewrites each table
        at the new width (ALTER TABLE ... EXPAND TABLE). Tables stay
        readable between phases because plans honor per-table numsegments
        (mixed-width, gp_policy.h:35 semantics)."""
        if self.dtm.current is not None and self.dtm.current.state == "active":
            raise SqlError("cannot expand inside a transaction")
        devs = self._devices
        if new_numsegments > len(devs):
            raise ValueError(
                f"cannot expand to {new_numsegments}: only {len(devs)} devices")
        if new_numsegments <= self.numsegments:
            raise ValueError("expansion must increase the segment count")
        # phase 1: new topology (existing entries, incl. FTS state, preserved)
        self.catalog.segments.expand(new_numsegments)
        self.numsegments = new_numsegments
        self.catalog._save()
        self.mesh = make_mesh(new_numsegments, devs)
        self.executor = Executor(self.catalog, self.store, self.mesh,
                                 new_numsegments, self.settings)
        self._select_cache.clear()
        self.fts.config = self.catalog.segments
        self.fts.mesh = self.mesh
        # phase 2: redistribute each table
        moved = {}
        for name in list(self.catalog.tables):
            schema = self.catalog.get(name)
            if schema.is_partitioned:
                # rewrite each child; the shared policy width flips once
                # (all children reference the parent's DistPolicy)
                moved[name] = sum(
                    self.store.rewrite_table(st, new_numsegments)
                    for st in schema.storage_tables())
            else:
                moved[name] = self.store.rewrite_table(name, new_numsegments)
        if self.replicator is not None:
            from greengage_tpu.runtime.replication import Replicator

            self.replicator = Replicator(self.store, self.catalog.segments)
        self._post_commit()
        return moved

    def set(self, name: str, value):
        self.settings.set(name, value)

    def close(self):
        # stop the background probers/heartbeats and send the gang a clean
        # stop frame (workers distinguish this from a coordinator crash)
        try:
            self.ingest.stop()   # drain-or-abort open streams first
        except Exception:
            pass
        try:
            # calibration state survives restart (promotion already kept
            # hot state: reconcile saves on every applied correction)
            self.feedback.save()
        except Exception:
            pass
        try:
            self.fts.stop()
        except Exception:
            pass
        if self._batch_server is not None:
            try:
                self._batch_server.stop()
            except Exception:
                pass
        if self.multihost is not None and self.multihost.is_coordinator \
                and self.multihost.channel is not None:
            try:
                self.multihost.channel.close()
            except Exception:
                pass


class _RWLock:
    """Write-path lock with a SHARED mode for per-table appenders.

    Exclusive = the classic session write lock (DDL, transactions,
    catalog moves, DELETE/UPDATE): one holder, re-entrant per thread.
    Shared = autocommit single-table appends (INSERT/COPY): any number of
    holders, each additionally serialized per TABLE by the session's
    table-lock map — so hot appenders to DIFFERENT tables stage and
    commit concurrently (their manifest commits are per-table delta CAS,
    storage/manifest.py) while anything structural still drains them.
    A waiting exclusive holder gates NEW shared entrants (no writer
    starvation); a thread holding exclusive may take shared (nested
    statement paths)."""

    def __init__(self):
        self._c = threading.Condition()
        self._excl: int | None = None     # owning thread ident
        self._depth = 0
        self._excl_waiting = 0
        self._shared: dict[int, int] = {}  # thread ident -> hold depth

    # exclusive (context manager: `with db._write_lock:`)
    def __enter__(self):
        me = threading.get_ident()
        with self._c:
            self._excl_waiting += 1
            try:
                while not (self._excl in (None, me)
                           and all(t == me for t in self._shared)):
                    # timed slices: a cancelled writer must leave the
                    # wait (statement cancellation point, PR-4 style)
                    self._c.wait(0.25)
                    check_interrupts()
            finally:
                self._excl_waiting -= 1
            self._excl = me
            self._depth += 1
        return self

    def __exit__(self, *a):
        with self._c:
            self._depth -= 1
            if self._depth == 0:
                self._excl = None
            self._c.notify_all()
        return False

    def shared(self):
        @_contextmanager
        def _shared_cm():
            me = threading.get_ident()
            with self._c:
                while (self._excl not in (None, me)
                       or (self._excl_waiting and self._excl is None
                           and me not in self._shared)):
                    # timed slices: cancelled appenders leave the wait
                    self._c.wait(0.25)
                    check_interrupts()
                self._shared[me] = self._shared.get(me, 0) + 1
            try:
                yield self
            finally:
                with self._c:
                    n = self._shared.get(me, 1) - 1
                    if n:
                        self._shared[me] = n
                    else:
                        self._shared.pop(me, None)
                    self._c.notify_all()

        return _shared_cm()


class _DegradedResult:
    """Result façade for statements served by the degraded-mode
    subprocess (worker death): rows come back JSON-decoded."""

    def __init__(self, columns, rows):
        self.columns = list(columns)
        self._rows = [tuple(r) for r in rows]
        self.stats = {"degraded": True}

    def rows(self):
        return self._rows

    def __len__(self):
        return len(self._rows)


class _NullSlot:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _EmptyScope:
    tables: list = []

    def resolve(self, parts):
        raise SqlError(f'column "{".".join(parts)}" does not exist')


def _zero_for(t: T.SqlType):
    if t.kind is T.Kind.TEXT:
        return ""
    return 0


def _reject_dml_subqueries(where) -> None:
    """IN/EXISTS in DML WHERE need dedicated survivor-semantics handling
    (x IN S being NULL must *survive* a DELETE); until then, fail clearly."""
    if where is None:
        return
    stack = [where]
    while stack:
        n = stack.pop()
        if isinstance(n, (A.InSubquery, A.ExistsExpr)):
            raise SqlError(
                "IN/EXISTS subqueries in DELETE/UPDATE WHERE are not "
                "supported yet")
        for f in ("left", "right", "arg", "lo", "hi", "else_"):
            v = getattr(n, f, None)
            if isinstance(v, A.ANode):
                stack.append(v)
        for v in getattr(n, "args", []) or []:
            stack.append(v)
        for v in getattr(n, "values", []) or []:
            if isinstance(v, A.ANode):
                stack.append(v)
        for cond, val in getattr(n, "whens", []) or []:
            stack.append(cond)
            stack.append(val)


def _sql_type_name(t: T.SqlType) -> tuple[str, tuple[int, ...]]:
    """SqlType -> (type name, typmod) for constructing CAST ASTs."""
    k = t.kind
    if k is T.Kind.DECIMAL:
        return "numeric", (38, t.scale)
    return {
        T.Kind.INT32: ("int", ()),
        T.Kind.INT64: ("bigint", ()),
        T.Kind.FLOAT64: ("double precision", ()),
        T.Kind.DATE: ("date", ()),
        T.Kind.BOOL: ("bool", ()),
        T.Kind.TEXT: ("text", ()),
    }[k]


_REC_COUNTER = __import__("itertools").count()


def _ddl_type(t) -> str:
    """SqlType -> DDL text for recursive-CTE materialization (DECIMAL
    degrades to double precision: host accumulation sees descaled
    floats)."""
    k = t.kind
    if k is T.Kind.INT32:
        return "int"
    if k is T.Kind.INT64:
        return "bigint"
    if k in (T.Kind.FLOAT64, T.Kind.DECIMAL):
        return "double precision"
    if k is T.Kind.BOOL:
        return "bool"
    if k is T.Kind.DATE:
        return "date"
    return "text"


def _rename_base_tables(node, mapping: dict):
    """Rewrite BaseTable references per ``mapping`` everywhere in the AST
    (including subqueries) — the worktable substitution."""

    if isinstance(node, A.BaseTable):
        if node.name in mapping:
            if node.alias is None:
                node.alias = node.name       # keep qualified refs valid
            node.name = mapping[node.name]
        return node
    if isinstance(node, A.ANode):
        for f in _dc.fields(node):
            v = getattr(node, f.name)
            setattr(node, f.name, _rename_base_tables(v, mapping))
        return node
    if isinstance(node, list):
        return [_rename_base_tables(v, mapping) for v in node]
    if isinstance(node, tuple):
        return tuple(_rename_base_tables(v, mapping) for v in node)
    return node


def _inferred_col(name: str, arr):
    """ColInfo-lite (name+type) from a host result array — the typing
    fallback for constant-only recursive base terms."""

    k = arr.dtype.kind
    if k == "M":
        t = T.DATE
    elif k == "b":
        t = T.BOOL
    elif k == "i" and arr.dtype.itemsize <= 4:
        t = T.INT32
    elif k in ("i", "u"):
        t = T.INT64
    elif k == "f":
        t = T.FLOAT64
    else:
        t = T.TEXT
    return SimpleNamespace(name=name, type=t)
