"""Vectorized serving — batch concurrent same-shape statements into one
XLA dispatch behind an async executor pipeline (docs/PERF.md
"Vectorized serving").

The QD/QE split amortizes planning across many executors; on TPU the
analogous lever is amortizing *dispatch* across many concurrent users. A
serving workload is dominated by repeated statement shapes with varying
literals, and PR 5 already reduced those to ONE executable keyed on the
literal-stripped signature with the literals as traced ``(1,)``-scalar
parameters. This module gives that parameter vector a batch axis:

  * **admission window** — the session-side intake collects in-flight
    statements sharing one plan-cache key (statement signature — which
    pins the shape signature at a given manifest version) for up to
    ``batch_window_ms``, or until ``batch_max_width`` members arrive. An
    idle pipeline flushes immediately, so the window costs latency only
    while the device is busy — exactly when the wait is free.
  * **one dispatch** — members' parameter vectors stack along a leading
    member axis and a width-bucketed batched program (compile.py
    ``batch_width``: the member body vmapped over the stacked params)
    runs ONCE over the shared staged inputs. Widths bucket to pow2 and
    the bucket joins the executor's program-cache key, so serving widths
    1..max_width costs log2(max_width) compiles, not max_width.
  * **pipelined stages** — a stager thread and a dispatcher thread
    connected by a queue: batch k+1 stages (host reads, PR-3 staging
    pool) while batch k runs on the device. Neither thread carries a
    statement context, so no member's cancellation can abort the batch.
  * **per-member demux** — each member's result slice finalizes exactly
    like a classic dispatch; a member cancelled mid-flight is masked out
    at demux (its thread raises the typed ``StatementCancelled``) and
    its batch-mates' results are untouched.
  * **observability** — every flush records a standalone trace (a
    ``batch-dispatch`` root with compile/stage/dispatch/fetch children
    plus one ``batch-member`` child per member) retired into the trace
    ring under a negative id AND grafted into every member's statement
    trace, so one flame graph shows the whole batch. Counters:
    ``batch_dispatch_total`` / ``batch_members_total`` /
    ``batch_window_flush_{full,timer}`` / ``batch_fallback_total``, the
    ``batch_width`` histogram, and the ``batch_queue_depth`` gauge.

Any batch that cannot run as one program — admission ceiling, overflow
flags, an unsignable shape — falls back: every member re-runs serially
through the classic executor path, which owns retries and spill. The
fallback is a routing decision, never a client-visible error.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import OrderedDict, deque

from greengage_tpu.exec.executor import BatchFallback
from greengage_tpu.runtime.interrupt import REGISTRY as _INTERRUPTS
from greengage_tpu.runtime.logger import counters, histograms
from greengage_tpu.runtime.trace import TRACES, Trace

# batch widths are small pow2s, not latencies: explicit buckets
WIDTH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

# hard ceiling on a member's wait for its flush — a wedged pipeline must
# degrade to serial execution, never to a hung client connection
_WEDGE_TIMEOUT_S = 600.0


class _Member:
    """One waiting statement: its parameter vector, interrupt context,
    statement trace, and the event its connection thread parks on.
    ``sql`` is the member's statement text — only set (and only needed)
    on a multihost coordinator, where the flush broadcasts the window's
    texts so the gang runs the same batched program."""

    __slots__ = ("pvec", "ctx", "trace", "wait_sid", "event", "result",
                 "fallback", "masked", "t0", "sql")

    def __init__(self, pvec, ctx, trace, sql=None):
        self.pvec = pvec
        self.ctx = ctx
        self.trace = trace
        self.sql = sql
        self.wait_sid = None
        self.event = threading.Event()
        self.result = None
        self.fallback = False     # re-run serially on the member's thread
        self.masked = False       # cancelled: raise, never read the slice
        self.t0 = time.monotonic()


class _Batch:
    """One admission window: same plan-cache key, stacked at flush."""

    __slots__ = ("bid", "key", "plan", "consts", "outs", "members",
                 "deadline", "trace", "root_sid", "staged", "stage_error",
                 "plan_hash")

    def __init__(self, bid, key, plan, consts, outs, deadline,
                 plan_hash=None):
        self.bid = bid
        self.key = key
        self.plan = plan
        self.consts = consts
        self.outs = outs
        self.members: list[_Member] = []
        self.deadline = deadline
        self.trace = None
        self.root_sid = None
        self.staged = None
        self.stage_error = None
        self.plan_hash = plan_hash    # gang broadcast verification


class BatchServer:
    """The per-Database serving pipeline. Created lazily by the session
    on the first batch-eligible statement; its two worker threads are
    daemons that carry no statement context."""

    def __init__(self, db):
        self.db = db
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._open: OrderedDict[str, _Batch] = OrderedDict()
        # windows that FILLED before the stager collected them: moved
        # here by submit() when it opens a successor window for the same
        # key — a full window must never be orphaned by its replacement
        self._full: deque = deque()
        self._dq: queue.Queue = queue.Queue()
        self._bids = itertools.count(1)
        self._members: dict[int, int] = {}   # statement id -> batch id
        self._inflight = 0     # batches popped from the window, not demuxed
        self._started = False
        # Event, not a bare bool: stop() runs on a statement thread
        # while both pipeline threads poll it (gg check races)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # finished per-flush traces, newest last (tests + introspection;
        # the same traces sit in the TRACES ring under their -bid ids)
        self.recent: deque = deque(maxlen=32)

    # ---- the statement-thread surface --------------------------------
    def submit(self, plan, consts, outs, key: str, pvec, sql=None,
               plan_hash=None):
        """Enroll the calling statement in the admission window for its
        plan-cache key and wait for the flush. Returns the member's
        Result, or None when the batch fell back (the caller re-runs the
        statement through the classic path). Raises StatementCancelled
        for a member cancelled while waiting or masked at demux. On a
        multihost coordinator the caller passes the statement text and
        plan hash so the flush can broadcast the window to the gang."""
        ctx = _INTERRUPTS.current()
        mtr = TRACES.current()
        m = _Member(pvec, ctx, mtr, sql=sql)
        self._ensure_threads()
        window_s = max(float(getattr(self.db.settings,
                                     "batch_window_ms", 2.0)), 0.0) / 1e3
        maxw = max(int(getattr(self.db.settings, "batch_max_width", 16)), 1)
        # the window is keyed by the BOUND PLAN's identity, not just the
        # statement signature: a concurrent DML bumps the manifest
        # version and the session re-binds (pinned string literals lower
        # to dictionary codes, est seeds move), so a member planned
        # after the commit must open its own window rather than execute
        # a batch-mate's stale binding. Plan objects are alive for the
        # window's lifetime (_Batch.plan holds a reference), so id() is
        # unambiguous here.
        wkey = (key, id(plan))
        qcap = int(getattr(self.db.settings, "batch_queue_limit", 0))
        with self._cv:
            if qcap > 0:
                waiting = sum(len(x.members) for x in self._open.values()) \
                    + sum(len(x.members) for x in self._full)
                if waiting >= qcap:
                    # serving-pipeline shed (docs/ROBUSTNESS.md "Overload
                    # protection"): past the member cap this statement
                    # runs on the classic serial path — bounded by the
                    # admission queue — instead of growing the windows
                    # unboundedly while the device is the bottleneck
                    counters.inc("batch_members_shed_total")
                    return None
            b = self._open.get(wkey)
            if b is not None and len(b.members) >= maxw:
                # the window filled before the stager collected it: hand
                # it over explicitly (replacing it in _open would orphan
                # its members) and open a successor for this member
                del self._open[wkey]
                self._full.append(b)
                b = None
            if b is None:
                b = _Batch(next(self._bids), key, plan, consts, outs,
                           time.monotonic() + window_s,
                           plan_hash=plan_hash)
                self._open[wkey] = b
            b.members.append(m)
            if ctx is not None:
                self._members[ctx.statement_id] = b.bid
            depth = sum(len(x.members) for x in self._open.values()) \
                + sum(len(x.members) for x in self._full)
            self._cv.notify_all()
        counters.set("batch_queue_depth", depth)
        if mtr is not None:
            m.wait_sid = mtr.begin("batch-wait", cat="queue", batch=b.bid)
        try:
            # the member's wait is a cancellation point: poll the
            # statement context so `gg cancel` / timeouts / disconnects
            # take a queued member out immediately — its batch-mates are
            # untouched (the dispatcher masks it at demux)
            hard = time.monotonic() + _WEDGE_TIMEOUT_S
            while not m.event.wait(0.02):
                if ctx is not None:
                    ctx.check()
                if self._stop.is_set():
                    # Database.close(): whatever this member's window
                    # was doing, degrade to the classic path rather
                    # than park the connection thread on a dead pipeline
                    self._abandon(wkey, b, m)
                    return None
                if time.monotonic() > hard:
                    if self._abandon(wkey, b, m):
                        return None   # window never flushed: run classic
                    # flushed but the pipeline is wedged mid-batch —
                    # degrade to serial rather than hang the connection
                    return None
        finally:
            if mtr is not None:
                mtr.end(m.wait_sid)
            if ctx is not None:
                with self._mu:
                    self._members.pop(ctx.statement_id, None)
        if m.masked and ctx is not None:
            ctx.check()   # raises the typed StatementCancelled
        if m.fallback or m.result is None:
            return None
        m.result.wall_ms = (time.monotonic() - m.t0) * 1e3
        return m.result

    def _abandon(self, wkey, b: _Batch, m: _Member) -> bool:
        """Remove a timed-out member from a still-open window (True) or
        report that its batch already flushed (False)."""
        with self._cv:
            if self._open.get(wkey) is b and m in b.members:
                b.members.remove(m)
                if not b.members:
                    del self._open[wkey]
                return True
        return False

    def member_of(self, statement_id: int) -> int | None:
        """Batch id a waiting statement belongs to (`gg ps` column)."""
        with self._mu:
            return self._members.get(statement_id)

    def queue_depths(self) -> dict:
        """Serving-pipeline depths for the status frame / `gg ps`."""
        with self._mu:
            return {
                "batch_admission_depth": sum(
                    len(b.members) for b in self._open.values())
                + sum(len(b.members) for b in self._full),
                "batch_inflight": self._inflight,
            }

    def stop(self) -> None:
        """Stop the pipeline threads and wait for them briefly (a daemon
        thread still inside an XLA dispatch at interpreter shutdown
        aborts the process from the C++ side), then release every member
        still parked in a window or staged batch — each degrades to the
        classic serial path on its own thread instead of waiting out the
        wedge timeout against a dead pipeline."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            if t is not threading.current_thread():
                # Database.close() teardown, not a statement path: the
                # pipeline threads exit on _stop within one poll tick and
                # the join is hard-bounded
                t.join(timeout=3.0)   # gg:ok(interrupts)
        stranded: list[_Member] = []
        with self._cv:
            for b in list(self._open.values()):
                stranded.extend(b.members)
            self._open.clear()
            while self._full:
                stranded.extend(self._full.popleft().members)
        while True:
            try:
                stranded.extend(self._dq.get_nowait().members)
            except queue.Empty:
                break
        for m in stranded:
            m.fallback = True
            m.event.set()

    # ---- pipeline threads --------------------------------------------
    def _ensure_threads(self) -> None:
        if self._started:
            return
        with self._mu:
            if self._started:
                return
            self._threads = [
                threading.Thread(target=self._stage_loop, daemon=True,
                                 name="gg-batch-stage"),
                threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name="gg-batch-dispatch"),
            ]
            for t in self._threads:
                t.start()
            self._started = True

    def _take_window(self) -> _Batch | None:
        """Block until a window is flushable. A window flushes when it is
        FULL (batch_max_width — whatever the pipeline is doing, staging
        it overlaps the in-flight dispatch), or when the pipeline can
        actually accept it (nothing already staged and waiting) and
        either the pipeline is idle (an extra wait would buy no
        batch-mates — flush immediately, so a lone statement pays ~zero
        window latency) or batch_window_ms has elapsed. While a staged
        batch is already queued behind the dispatcher, windows keep
        accumulating members — the wait is free exactly when the device
        is the bottleneck, and width grows to match the device's pace."""
        with self._cv:
            while not self._stop.is_set():
                now = time.monotonic()
                maxw = max(int(getattr(self.db.settings,
                                       "batch_max_width", 16)), 1)
                while self._full:
                    b = self._full.popleft()
                    if not b.members:
                        continue
                    self._inflight += 1
                    counters.inc("batch_window_flush_full")
                    return b
                idle = (self._inflight == 0 and self._dq.empty())
                can_take = self._dq.empty() and self._inflight <= 1
                for key, b in list(self._open.items()):
                    full = len(b.members) >= maxw
                    if full or (can_take and (idle or now >= b.deadline)):
                        del self._open[key]
                        if not b.members:
                            continue   # every member abandoned
                        self._inflight += 1
                        if full:
                            counters.inc("batch_window_flush_full")
                        else:
                            counters.inc("batch_window_flush_timer")
                        return b
                timeout = 0.25
                if self._open and can_take:
                    timeout = min(max(
                        min(x.deadline for x in self._open.values()) - now,
                        0.001), 0.25)
                # pipeline thread: no statement context to poll — members
                # poll their own contexts in submit()
                self._cv.wait(timeout)   # gg:ok(interrupts)
        return None

    def _stage_loop(self) -> None:
        """Admission -> stage: pop flushable windows and stage them (the
        compile-or-reuse + admission + host data path), overlapping the
        dispatcher's device stage — statement k+1 stages while statement
        k runs on device (the PR-3 staging pool extended past a single
        statement)."""
        while not self._stop.is_set():
            try:
                b = self._take_window()
                if b is None:
                    return
                bt = Trace(-b.bid, f"batch {b.key[:300]}")
                b.trace = bt
                b.root_sid = bt.begin("batch-dispatch", cat="batch",
                                      batch=b.bid, width=len(b.members))
                TRACES.adopt(bt)
                try:
                    b.staged = self.db.executor.prepare_batch(
                        b.plan, b.consts, b.outs, b.key,
                        [m.pvec for m in b.members])
                except BaseException as e:
                    b.staged = None
                    b.stage_error = e
                finally:
                    TRACES.release(bt)
                self._dq.put(b)
                self._refresh_depth()
            except Exception:
                # the pipeline must survive anything — members time out
                # into the serial path rather than hang; no statement
                # runs on this thread, so there is nothing to poll
                time.sleep(0.01)   # gg:ok(interrupts)

    def _dispatch_loop(self) -> None:
        """Dispatch -> fetch -> demux: run staged batches on the device
        one at a time and hand every member its slice."""
        while not self._stop.is_set():
            try:
                # pipeline thread: members poll their own contexts
                b = self._dq.get(timeout=0.25)   # gg:ok(interrupts)
            except queue.Empty:
                continue
            # the staged queue just drained: wake the stager so the next
            # window flushes and stages WHILE this batch is on the device
            with self._cv:
                self._cv.notify_all()
            try:
                self._run_batch(b)
            except Exception:
                for m in b.members:
                    m.fallback = True
                    m.event.set()
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _run_batch(self, b: _Batch) -> None:
        ex = self.db.executor
        bt = b.trace
        fell_back = False
        TRACES.adopt(bt)
        try:
            if b.staged is None:
                raise BatchFallback(f"stage failed: {b.stage_error!r}")
            comp, inputs, snapshot, compiled = b.staged
            mh_cm = self._mh_exchange(b)
            if mh_cm is not None:
                # multihost gang: two-phase broadcast of the batch window
                # (readiness acks -> 'go' -> concurrent dispatch ->
                # completion acks); any refusal/failure raises
                # BatchFallback so members re-run via the classic
                # per-statement dispatch, which owns failover
                with mh_cm:
                    flat = ex.dispatch_batch(comp, inputs)
            else:
                flat = ex.dispatch_batch(comp, inputs)
            over = ex.batch_overflowed(comp, flat)
            if over:
                # per-member capacity needs differ (value-dependent join
                # expansion / group counts): the serial path's tier
                # machinery owns the retry — never retry the whole batch
                raise BatchFallback(
                    f"overflow flags {over} at width {len(b.members)}")
            width = len(b.members)
            counters.inc("batch_dispatch_total")
            counters.inc("batch_members_total", width)
            histograms.observe("batch_width", float(width),
                               buckets=WIDTH_BUCKETS)
            for i, m in enumerate(b.members):
                cancelled = m.ctx is not None and m.ctx.cancelled
                with bt.span("batch-member", cat="batch", slot=i,
                             statement=(m.ctx.statement_id
                                        if m.ctx is not None else None),
                             cancelled=bool(cancelled)):
                    if cancelled:
                        # masked out at demux: the member's thread raises
                        # the typed cancellation; its batch-mates keep
                        # their results
                        m.masked = True
                        continue
                    try:
                        res = ex.demux_batch(comp, flat, i, snapshot)
                    except Exception:
                        m.fallback = True   # lone demux hiccup: serial
                        continue
                    res.stats = {
                        "batched": True,
                        "batch_id": b.bid,
                        "batch_width": width,
                        "batch_bucket": comp.batch_width,
                        "compiled": bool(compiled),
                        "segments": ex.nseg,
                        "rows_out": len(res),
                    }
                    m.result = res
        except BatchFallback:
            counters.inc("batch_fallback_total")
            fell_back = True
        except BaseException:
            counters.inc("batch_fallback_total")
            fell_back = True
        finally:
            TRACES.release(bt)
            bt.end(b.root_sid)
            TRACES.retire(bt)
            self.recent.append(bt)
            if fell_back:
                for m in b.members:
                    m.fallback = True
            self._graft(b, bt)
            for m in b.members:
                m.event.set()
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()
            self._refresh_depth()

    def _mh_exchange(self, b: _Batch):
        """Context manager broadcasting this window to the worker gang
        (session._mh_batch_exchange), or None on a single-host Database.
        Raises BatchFallback when a member lacks its statement text —
        the gang cannot replay what it cannot see."""
        db = self.db
        mh = getattr(db, "multihost", None)
        if mh is None or not getattr(mh, "is_coordinator", False):
            return None
        sqls = [m.sql for m in b.members]
        if not all(sqls):
            raise BatchFallback(
                "batched member lacks statement text for the gang "
                "broadcast")
        return db._mh_batch_exchange(sqls, b.plan_hash)

    # ---- bookkeeping --------------------------------------------------
    def _graft(self, b: _Batch, bt: Trace) -> None:
        """Copy the flush's span tree into every member's statement trace
        under its batch-wait span, re-based onto the member's clock — one
        flame graph shows the whole batch from any member's trace."""
        spans = bt.export()
        for m in b.members:
            if m.trace is None or m.wait_sid is None:
                continue
            try:
                base_ms = (bt.wall0 - m.trace.wall0) * 1e3
                m.trace.graft(spans, m.wait_sid, tid=f"batch-{b.bid}",
                              base_ms=base_ms)
            except Exception:
                pass   # a lost graft must never lose the statement

    def _refresh_depth(self) -> None:
        with self._mu:
            depth = sum(len(x.members) for x in self._open.values()) \
                + sum(len(x.members) for x in self._full)
        counters.set("batch_queue_depth", depth)
