"""Double-buffered bucket schedules — the host half of pipelined motion.

The PR-3/PR-11 work overlapped staging with device dispatch *inside* one
program; bucketed schedules (spill dedupe buckets, window spill buckets,
tiered-workfile promotion) still ran stage -> compute as strictly serial
phases, so the device idled during every bucket's host preparation and
the host idled during every bucket's device program. This module supplies
the missing overlap: ``run_pipeline(items, stage, compute)`` runs the
``stage`` callable for bucket k+1 on a background thread while the
calling thread runs ``compute`` for bucket k — double-buffered (the
stager keeps at most one bucket ahead), so host memory holds at most two
staged buckets and the schedule's wall time tends to
max(sum(stage), sum(compute)) instead of their sum.

Determinism note (multihost lockstep): ``compute`` always runs on the
CALLING thread in bucket order — only the side-effect-free ``stage``
work moves off-thread — so collective programs and spill schedules stay
bit-identical to the serial loop. The ``motion_pipeline`` GUC (or a
single-bucket schedule) falls back to the serial loop with the same
span structure, which is the microbench baseline.

Spans: every bucket records ``motion-stage`` / ``motion-compute``
(cat="motion") with index/total; the realized stage(k+1) x compute(k)
overlap accumulates into the ``motion_overlap_ms`` counter and is what
the trace-timestamp overlap test asserts on.
"""

from __future__ import annotations

import threading
import time

from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime import trace as _trace
from greengage_tpu.runtime.logger import counters


class _Slot:
    __slots__ = ("value", "err", "t0", "t1")


class BucketPipeline:
    """One schedule's staging thread + slot exchange. Shared between the
    statement thread (take/close) and its stager; all slot state moves
    under the one condition lock."""

    def __init__(self, items, stage, trace, label: str):
        self.items = items
        self.stage = stage
        self.trace = trace
        self.label = label
        self._mu = threading.Condition(threading.Lock())
        self._slots: dict[int, _Slot] = {}
        self._consumed = -1          # highest index take() handed out
        self._stop = False
        # the spawning statement's interrupt context: the stager polls it
        # between buckets so a cancelled statement's pipeline dies at the
        # next bucket boundary (close() below never outwaits it)
        self._ctx = interrupt.REGISTRY.current()
        self._thread = threading.Thread(target=self._stage_loop,
                                        daemon=True, name="gg-motion-stage")

    def start(self) -> None:
        self._thread.start()

    def _stage_loop(self) -> None:
        tr = self.trace
        if tr is not None:
            _trace.TRACES.adopt(tr)   # spans land in the statement trace
        try:
            n = len(self.items)
            for i, it in enumerate(self.items):
                with self._mu:
                    # double buffer: at most ONE bucket staged ahead of
                    # the one the consumer is computing
                    while not self._stop and i - self._consumed > 1:
                        self._mu.wait(0.1)   # gg:ok(interrupts) — bounded
                        # wait on the pipeline's own condition; the
                        # statement thread owns cancellation and take()
                        # polls it
                    if self._stop:
                        return
                if self._ctx is not None and self._ctx.cancelled:
                    return
                slot = _Slot()
                slot.t0 = time.monotonic()
                try:
                    with _trace.span("motion-stage", cat="motion", index=i,
                                     total=n, label=self.label):
                        # fault point INSIDE the stage span: a 'sleep'
                        # injection widens stage(k+1) so the overlap test
                        # pins it across compute(k) deterministically
                        faults.check("motion_bucket")
                        slot.value, slot.err = self.stage(it, i), None
                except BaseException as e:   # re-raised at take(i)
                    slot.value, slot.err = None, e
                slot.t1 = time.monotonic()
                with self._mu:
                    self._slots[i] = slot
                    self._mu.notify_all()
                if slot.err is not None:
                    return
        finally:
            if tr is not None:
                _trace.TRACES.release(tr)

    def take(self, i: int) -> _Slot:
        """Block until bucket i is staged; marks it consumed (which frees
        the stager to run bucket i+1 while the caller computes i)."""
        with self._mu:
            self._consumed = max(self._consumed, i)
            self._mu.notify_all()
            while i not in self._slots:
                interrupt.check_interrupts()
                self._mu.wait(0.1)
            slot = self._slots.pop(i)
        if slot.err is not None:
            raise slot.err
        return slot

    def close(self) -> None:
        """Stop + join the stager, bounded; polls the statement's
        cancellation like PassPrefetcher.close so a dying statement never
        sits out a wedged stage callable."""
        with self._mu:
            self._stop = True
            self._mu.notify_all()
        t = self._thread
        if not t.is_alive():
            return
        deadline = time.monotonic() + 60.0
        while t.is_alive() and time.monotonic() < deadline:
            if self._ctx is not None and self._ctx.cancelled:
                t.join(timeout=5.0)
                break
            t.join(timeout=0.25)


def run_pipeline(items, stage, compute, settings=None, label: str = "spill"):
    """Run every item through stage -> compute in item order, overlapping
    stage(k+1) with compute(k) on a background thread. ``stage(item, i)``
    must be side-effect-free host work (reads, decodes, mask builds);
    ``compute(staged, item, i)`` runs on the calling thread. Returns the
    list of compute results. Serial (same spans, no thread) when the
    motion_pipeline GUC is off or the schedule has a single bucket."""
    n = len(items)
    enabled = n > 1 and (settings is None
                         or bool(getattr(settings, "motion_pipeline", True)))
    out = []
    if not enabled:
        for i, it in enumerate(items):
            interrupt.check_interrupts()
            with _trace.span("motion-stage", cat="motion", index=i,
                             total=n, label=label):
                faults.check("motion_bucket")
                staged = stage(it, i)
            with _trace.span("motion-compute", cat="motion", index=i,
                             total=n, label=label):
                out.append(compute(staged, it, i))
        return out
    pipe = BucketPipeline(items, stage, _trace.TRACES.current(), label)
    pipe.start()
    overlap_s = 0.0
    try:
        prev = None                    # compute window of bucket i-1
        for i, it in enumerate(items):
            interrupt.check_interrupts()
            slot = pipe.take(i)
            c0 = time.monotonic()
            with _trace.span("motion-compute", cat="motion", index=i,
                             total=n, label=label):
                out.append(compute(slot.value, it, i))
            c1 = time.monotonic()
            if prev is not None:       # stage(i) overlapped compute(i-1)?
                overlap_s += max(0.0, min(slot.t1, prev[1])
                                 - max(slot.t0, prev[0]))
            prev = (c0, c1)
    finally:
        pipe.close()
        if overlap_s > 0.0:
            counters.inc("motion_overlap_ms", max(int(overlap_s * 1e3), 1))
    return out
