"""Host-offload spill: pass-partitioned execution past HBM capacity.

The workfile-manager role (reference: src/backend/utils/workfile_manager/
workfile_mgr.c:544, hybrid hash agg spilling in execHHashagg.c) rethought
for the TPU memory hierarchy: host RAM plays the workfile, and the unit of
spilling is a whole EXECUTION PASS instead of a hash batch.

Applicability: plans whose below-gather tree is
    [Sort|Limit|Project|Filter]* FinalAggregate( Motion( PartialAggregate(
        probe-linear subtree )))
— every TPC-H-style join+GROUP BY/scalar aggregate. The probe-linear
subtree is row-linear in one big table (joins only fan out on their PROBE
side; builds stay whole), so partitioning that table's rows into P chunks
and running the subtree + PARTIAL aggregate per chunk yields partial
states whose union merges exactly in the FINAL aggregate:

    pass p:  chunk_p -> joins -> partial agg   (fits in HBM)
             gather partial rows to host       (small)
    merge:   final plan with the partial subtree replaced by a host-staged
             input of all passes' partial rows

This completes any such query whose PER-PASS working set fits, instead of
rejecting it at the vmem admission check.
"""

from __future__ import annotations
import copy
import itertools

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime import memaccount
from greengage_tpu.runtime import trace as _trace
from greengage_tpu.runtime.logger import counters
from greengage_tpu.planner.locus import Locus
from greengage_tpu.planner.logical import (Aggregate, ColInfo, Filter, Join,
                                           Limit, Motion, MotionKind,
                                           PartialState, Plan, Project, Scan,
                                           Sort, Window)


class NotSpillable(ValueError):
    """The plan's shape cannot be pass-partitioned soundly."""


def partial_state_cols(partial: Aggregate) -> list:
    """ColInfos for a partial Aggregate's actual output: group keys plus
    the @c/@s/@m state columns the final phase merges (the compiler's
    partial-phase naming contract, exec/compile.py _c_aggregate)."""
    # keys re-exposed with name == id: the host-input staging maps columns
    # by storage NAME, and the ephemeral table's storage names are the ids
    out = [ColInfo(ci.id, ci.type, ci.id, ci.dict_ref)
           for ci, _ in partial.group_keys]
    for ci, a in partial.aggs:
        if a.func in ("count", "count_star"):
            out.append(ColInfo(ci.id + "@c", T.INT64, ci.id + "@c"))
        elif a.func == "sum":
            out.append(ColInfo(ci.id + "@s", a.type, ci.id + "@s"))
        elif a.func == "avg":
            stype = E.agg_result_type("sum", a.arg.type)
            out.append(ColInfo(ci.id + "@s", stype, ci.id + "@s"))
            out.append(ColInfo(ci.id + "@c", T.INT64, ci.id + "@c"))
        elif a.func in ("min", "max"):
            out.append(ColInfo(ci.id + "@m", a.arg.type, ci.id + "@m",
                               dict_ref=getattr(a.arg, "_dict_ref", None)))
    return out

_WRAPPERS = (Sort, Limit, Project, Filter)


def find_spill_split(plan: Motion):
    """-> (capture_agg, replace_target) of the DEEPEST reduction point on
    the plan's spine, or None.

    A reduction point is an Aggregate whose output rows merge exactly
    across disjoint input partitions:
      - a partial aggregate (states are sums/counts/min/max: additive) —
        capture its STATE columns, swap the partial itself in the merge;
      - a keys-only "dedupe" aggregate (DISTINCT level: dedupe is
        idempotent under union — dedupe(∪ dedupe(chunk)) = dedupe(∪)) —
        capture its key rows, swap the subtree BELOW its redistribute
        Motion so the merge re-hashes the union before re-deduping.
    The walk descends through wrappers, motions, and single-phase
    aggregates so a DISTINCT dedupe buried under the outer aggregate's
    own phases is still found (execHHashagg.c spills the dedupe level the
    same way)."""
    node = plan.child
    best = None
    while True:
        while isinstance(node, _WRAPPERS):
            node = node.child
        if (isinstance(node, Aggregate) and node.phase == "final"
                and isinstance(node.child, Motion)
                and isinstance(node.child.child, Aggregate)
                and node.child.child.phase == "partial"):
            partial = node.child.child
            best = (partial, partial, False)
            node = partial.child
            continue
        if (isinstance(node, Aggregate) and node.phase == "single"
                and not node.aggs and node.group_keys
                # the merge re-runs this dedupe over host-staged rows
                # carrying the OUTPUT ids, so every key must be a plain
                # pass-through column (the binder's id invariant)
                and all(isinstance(e, E.ColRef) and e.name == ci.id
                        for ci, e in node.group_keys)):
            if (isinstance(node.child, Motion)
                    and node.child.kind is MotionKind.REDISTRIBUTE):
                # the existing motion re-hashes the merge's union rows,
                # co-locating cross-pass duplicates before the re-dedupe
                best = (node, node.child.child, False)
                node = node.child.child
            else:
                # colocated dedupe (input already hashed on the keys):
                # duplicates of a key can still span PASSES, and the
                # contiguous host staging scatters them across segments —
                # the merge must insert a redistribute of its own
                best = (node, node.child, True)
                node = node.child
            continue
        if isinstance(node, Aggregate) and node.phase == "single":
            node = node.child
            continue
        if isinstance(node, Motion) and node.kind is MotionKind.REDISTRIBUTE:
            node = node.child
            continue
        break
    return best


def spill_candidate_tables(plan: Plan) -> list[str]:
    """Tables over whose row-partitions the subtree's OUTPUT is a disjoint
    union — partitioning any of them into passes is sound below an
    (order-insensitive) reduction point.

    Probe-side descent is always sound (each probe row lives in exactly
    one chunk). Build-side descent is sound only through INNER (and
    cross) joins: a chunked build partitions each probe row's matches
    across passes, which unions exactly for inner joins but double-counts
    semi joins and null-extends left joins per pass — the grace-join
    batching analog (nodeHashjoin.c) restricted the same way. Aggregates,
    windows, unions, sorts, and limits end soundness (limit/sort are not
    union-distributive; a nested agg is its own reduction point)."""
    out = []

    def walk(node):
        if isinstance(node, Scan):
            out.append(node.table)
            return
        if isinstance(node, Join):
            walk(node.left)
            if node.kind in ("inner", "cross") and not node.null_aware:
                walk(node.right)
            return
        if isinstance(node, (Project, Filter, Motion)):
            walk(node.child)

    walk(plan)
    return out


def count_scans(plan: Plan, table: str) -> int:
    n = 0
    stack = [plan]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan) and p.table == table:
            n += 1
        stack.extend(p.children)
    return n



def _charge_spill(cols: dict, valids: dict, item: str) -> None:
    """Account the host-resident captured rows (the workfile bytes) to
    the statement's 'spill' owner (runtime/memaccount.py)."""
    nb = sum(int(getattr(a, "nbytes", 0)) for a in cols.values())
    nb += sum(int(getattr(a, "nbytes", 0)) for a in valids.values()
              if a is not None)
    memaccount.charge("spill", nb, item=item)


def _collect_passes(cols_spec, results):
    """Merge per-pass Result columns on the host with shared validity
    defaulting: -> (cols, valids) where valids[c] is None when every pass
    reported the column all-valid. The merged buffers are PREALLOCATED
    from the pass row counts and filled in place — the old append-then-
    np.concatenate pair transiently held a second full copy of the
    workfile at the merge peak."""
    per_pass = []                      # (rows, {id: (arr, valids|None)})
    any_invalid = {c.id: False for c in cols_spec}
    total = 0
    for res in results:
        data = {}
        rows = 0
        for c in cols_spec:
            a = np.asarray(res.cols[c.id])
            rows = len(a)
            v = res.valids.get(c.id)
            if v is not None:
                v = np.asarray(v, bool)
                any_invalid[c.id] = True
            data[c.id] = (a, v)
        per_pass.append((rows, data))
        total += rows
    dtypes = {}
    for _rows, data in per_pass:
        for cid, (a, _v) in data.items():
            dtypes[cid] = (a.dtype if cid not in dtypes
                           else np.result_type(dtypes[cid], a.dtype))
    cols = {c.id: np.empty(total, dtype=dtypes.get(c.id, np.int64))
            for c in cols_spec}
    valids = {c.id: (np.ones(total, dtype=bool)
                     if any_invalid[c.id] else None) for c in cols_spec}
    off = 0
    for rows, data in per_pass:
        for c in cols_spec:
            a, v = data[c.id]
            cols[c.id][off:off + rows] = a
            if valids[c.id] is not None and v is not None:
                valids[c.id][off:off + rows] = v
        off += rows
    return cols, valids


def _size_chunk_passes(executor, consts, pass_plan, candidates,
                       limit_bytes):
    """Largest-first chunk-size search shared by the partial-aggregate
    and window spills: pick partition tables and per-pass chunk rows
    that bring the compiled pass program's est_bytes under the limit
    (multiple tables = the grace chunk grid). -> (chosen {table: chunk},
    per_table [(table, chunk, n)], total passes, probe CompileResult);
    raises NotSpillable when no combination fits or the grid explodes."""
    from greengage_tpu.exec.compile import Compiler

    store = executor.store
    settings = executor.settings
    candidates = sorted(
        candidates, key=lambda t: -max(store.segment_rowcounts(t),
                                       default=0))
    floor = 1 << 12
    MAX_PASSES = 256
    chosen: dict[str, int] = {}          # table -> chunk rows
    comp = None
    fits = False
    for cand in candidates:
        max_rows = max(store.segment_rowcounts(cand), default=0)
        if max_rows == 0:
            continue
        chunk = max_rows
        while True:
            chunk = max(chunk // 2, floor)
            over = dict(chosen)
            over[cand] = chunk
            comp = Compiler(executor.catalog, store, executor.mesh,
                            executor.nseg, consts, settings,
                            scan_cap_override=over,
                            no_direct=True).compile(pass_plan)
            if comp.est_bytes <= limit_bytes * 0.7 or chunk == floor:
                break
        chosen[cand] = chunk
        if comp.est_bytes <= limit_bytes:
            fits = True
            break
    if not fits:
        raise NotSpillable("per-pass working set still exceeds the limit "
                           "for every partitionable table combination")
    per_table = []                        # (table, chunk, npasses)
    npasses = 1
    for t, chunk in chosen.items():
        max_rows = max(store.segment_rowcounts(t), default=0)
        n = -(-max_rows // chunk)
        per_table.append((t, chunk, n))
        npasses *= n
    if npasses > MAX_PASSES:
        raise NotSpillable(
            f"spill would need {npasses} passes (> {MAX_PASSES})")
    return chosen, per_table, npasses, comp


def spill_run(executor, plan: Motion, consts, out_cols, raw: bool,
              instrument: bool = False):
    """Execute ``plan`` in partitioned passes. Raises ValueError when the
    plan shape is not spillable (caller surfaces the vmem rejection).
    ``instrument`` (EXPLAIN ANALYZE) collects per-node row counts from
    every pass and the merge program, summed back onto the ORIGINAL plan's
    node identities (the pass subtree shares node objects with the plan;
    the merge path's clones are remapped via _replace_child's node map)."""
    split = find_spill_split(plan)
    if split is None:
        raise NotSpillable("plan shape not spillable")
    capture_agg, replace_target, add_motion = split
    subtree = (capture_agg.child if capture_agg is replace_target
               else replace_target)
    candidates = [t for t in spill_candidate_tables(subtree)
                  if not t.startswith("@") and count_scans(plan, t) == 1]
    if not candidates:
        raise NotSpillable("no partitionable table below the reduction point")
    store = executor.store

    from greengage_tpu.exec.executor import effective_limit_bytes

    settings = executor.settings
    limit_bytes = effective_limit_bytes(settings)

    # pass program: gather the reduction point's output rows (partial
    # STATE columns / dedupe keys; raw storage representation — finalize
    # must not decode)
    state_cols = partial_state_cols(capture_agg)
    capture = PartialState(capture_agg, state_cols)
    capture.locus = capture_agg.locus
    capture.est_rows = capture_agg.est_rows
    pass_plan = Motion(MotionKind.GATHER, capture)
    pass_plan.locus = Locus.entry()

    # choose the partition tables (largest first — probe side AND/OR
    # inner-join build sides, the grace-join regime: when both sides of a
    # join exceed HBM, BOTH are range-partitioned and the passes walk the
    # cartesian chunk grid, exactly nodeHashjoin.c's batch x batch
    # schedule but with whole execution passes) and the chunk sizes that
    # bring the pass program under the limit
    chosen, per_table, npasses, comp = _size_chunk_passes(
        executor, consts, pass_plan, candidates, limit_bytes)
    # lockstep parity: the pass schedule every gang member must agree on
    executor.note_spill_schedule(
        "agg", passes=npasses,
        chunks=[[t, c, n] for t, c, n in per_table])

    # run the passes, landing partial rows in the tiered workfile (host
    # RAM, overflowing to compressed disk segments — exec/workfile.py).
    # While pass k's jitted program runs, a background thread warms pass
    # k+1's cold block reads into the block cache (exec/staging.py; all
    # passes share the same committed files, so after the budget-resident
    # first pass this is a cheap cache probe)

    from greengage_tpu.exec import staging as _staging
    from greengage_tpu.exec import workfile as _workfile

    grids = [[(t, (i * c, (i + 1) * c)) for i in range(n)]
             for t, c, n in per_table]
    caps = {t: c for t, c, _ in per_table}
    partial_cols = state_cols
    combos = list(itertools.product(*grids))
    prefetcher = _staging.PassPrefetcher(
        executor, comp.input_spec, store.manifest.snapshot())
    wf = _workfile.SpillWorkfile(executor, partial_cols, "partials")
    try:
        try:
            for i, combo in enumerate(combos):
                # spill pass boundary = CHECK_FOR_INTERRUPTS (the
                # cleaner's documented cancellation point; user cancels
                # land here too)
                interrupt.check_interrupts()
                if i + 1 < len(combos):
                    prefetcher.kick()
                with _trace.span("spill-pass", cat="spill", index=i,
                                 total=len(combos)):
                    wf.add(executor.run_single(
                        pass_plan, consts, partial_cols, raw=True,
                        scan_cap_override=caps,
                        row_ranges=dict(combo), no_direct=True,
                        instrument=instrument))
        finally:
            prefetcher.close()
        aux_cols, aux_valids = wf.assemble()

        # merge program: the original plan with the replace target
        # swapped for a host input of the merged captured rows. Partial
        # case: the partial itself is replaced (its states redistribute +
        # final-merge above). Dedupe case: the subtree BELOW the dedupe's
        # redistribute is replaced, so the union re-hashes (co-locating
        # cross-pass duplicates) and the dedupe re-runs on device.
        aux_name = "@spill:partials"
        host_scan = Scan(aux_name, list(partial_cols))
        host_scan.locus = (capture_agg.locus
                           if capture_agg is replace_target
                           else Locus.strewn(executor.nseg))
        host_scan.est_rows = float(len(next(iter(aux_cols.values()), [])))
        repl: Plan = host_scan
        if add_motion:
            key_cols = [ci for ci, _ in capture_agg.group_keys]
            m = Motion(MotionKind.REDISTRIBUTE, host_scan,
                       hash_exprs=[E.ColRef(ci.id, ci.type)
                                   for ci in key_cols])
            m.locus = Locus.hashed(tuple(ci.id for ci in key_cols),
                                   executor.nseg)
            m.est_rows = host_scan.est_rows
            repl = m
        node_map: dict = {}
        merged = _replace_child(plan, replace_target, repl, node_map)
        from greengage_tpu.exec.executor import AdmissionError

        try:
            with _trace.span("spill-merge", cat="spill", passes=npasses):
                res = executor.run_single(
                    merged, consts, out_cols, raw=raw,
                    aux_tables={aux_name: (aux_cols, aux_valids)},
                    no_direct=True, instrument=instrument)
        except AdmissionError:
            if capture_agg.aggs:      # partial-state merges never regress
                raise
            # recursive-merge level (execHHashagg.c batch recursion): the
            # dedupe working set (~the full key domain for near-unique
            # keys) exceeds HBM even after pass capture. Partition the
            # captured keys BY KEY HASH into disjoint buckets — dedupe is
            # exact per bucket, and the additive partial states above the
            # dedupe sum exactly across buckets.
            res, extra = _bucketed_dedupe_merge(
                executor, merged, capture_agg, host_scan, aux_name,
                aux_cols, aux_valids, consts, out_cols, raw, limit_bytes)
            if instrument:
                _merge_node_rows(res, wf.stats, node_map)
            return res, npasses + extra
        if instrument:
            _merge_node_rows(res, wf.stats, node_map)
        return res, npasses
    finally:
        wf.close()


def _merge_node_rows(res, pass_stats, node_map) -> None:
    """EXPLAIN ANALYZE accounting across spill passes: per-node row
    counts from the pass programs' stats dicts (their subtree nodes ARE
    the original plan's objects) sum with the merge program's (clone ids
    remapped to their originals), landing in the final Result's stats
    under the ORIGINAL plan-node identities the session's describe()
    walk uses. ``pass_stats`` is a list of per-pass Result.stats dicts
    (the tiered workfile retains stats, not whole Results)."""
    agg: dict = {}
    for st in pass_stats:
        for nid, n in (((st or {}).get("node_rows")) or {}).items():
            agg[nid] = agg.get(nid, 0) + n
    if isinstance(res.stats, dict):
        for nid, n in ((res.stats.get("node_rows")) or {}).items():
            nid = node_map.get(nid, nid)
            agg[nid] = agg.get(nid, 0) + n
    else:
        res.stats = {}
    res.stats["node_rows"] = agg


def _find_partial_above(plan: Plan, target: Plan):
    """DEEPEST final->Motion->partial aggregate pattern whose partial
    subtree contains ``target``."""
    found = None

    def walk(node):
        nonlocal found
        if (isinstance(node, Aggregate) and node.phase == "final"
                and isinstance(node.child, Motion)
                and isinstance(node.child.child, Aggregate)
                and node.child.child.phase == "partial"
                and _contains(node.child.child, target)):
            found = node.child.child
        for c in node.children:
            walk(c)

    walk(plan)
    return found


def _bucket_hash(aux_cols, aux_valids, key_ids) -> np.ndarray:
    from greengage_tpu.storage import native

    n = len(next(iter(aux_cols.values())))
    h = np.full(n, 0x9E3779B9, np.uint32)
    for kid in key_ids:
        a = np.asarray(aux_cols[kid])
        if a.dtype.kind == "f":
            # hashfloat8 parity (ops/hashing._canon_f64): -0.0 -> 0.0 and
            # all NaN payloads -> one pattern, or equal keys split buckets
            a = a.astype(np.float64)
            a = np.where(np.isnan(a), np.float64("nan"), a + 0.0)
            a = a.view(np.int64)
        hk = native.hash_i64(a.astype(np.int64))
        v = aux_valids.get(kid)
        if v is not None:
            hk = np.where(np.asarray(v, bool), hk, np.uint32(0x27D4EB2F))
        h = native.hash_combine(h, hk)
    return h


def _bucketed_dedupe_merge(executor, merged, dedupe, host_scan, aux_name,
                           aux_cols, aux_valids, consts, out_cols, raw,
                           limit_bytes):
    """Run the merge in key-hash buckets, capturing the outer partial
    aggregate's states per bucket; one small final pass merges them."""
    # anchor on the host scan: _replace_child shallow-copied every node on
    # the path, so the dedupe OBJECT from the original tree is not in
    # ``merged`` — but the inserted host scan is (by reference)
    outer_partial = _find_partial_above(merged, host_scan)
    if outer_partial is None:
        raise NotSpillable(
            "dedupe working set exceeds the limit and no additive "
            "aggregate sits above the distinct level to merge buckets")
    key_ids = [ci.id for ci, _ in dedupe.group_keys]
    h = _bucket_hash(aux_cols, aux_valids, key_ids)

    state_cols = partial_state_cols(outer_partial)
    capture = PartialState(outer_partial, state_cols)
    capture.locus = outer_partial.locus
    capture.est_rows = outer_partial.est_rows
    bucket_plan = Motion(MotionKind.GATHER, capture)
    bucket_plan.locus = Locus.entry()

    # size K against the COMPILED per-bucket estimate (bucket 0 as the
    # representative subset; the hash is uniform)
    from greengage_tpu.exec.compile import Compiler

    K = 2
    while True:
        m0 = (h % np.uint32(K)) == 0
        sub = {k: np.asarray(v)[m0] for k, v in aux_cols.items()}
        subv = {k: (np.asarray(v, bool)[m0] if v is not None else None)
                for k, v in aux_valids.items()}
        comp = Compiler(executor.catalog, executor.store, executor.mesh,
                        executor.nseg, consts, executor.settings,
                        aux_tables={aux_name: (sub, subv)},
                        no_direct=True).compile(bucket_plan)
        if comp.est_bytes <= max(limit_bytes, 1) * 0.9 or K >= 64:
            break
        K *= 2
    if comp.est_bytes > limit_bytes:
        raise NotSpillable(
            "per-bucket dedupe working set still exceeds the limit at 64 "
            "merge buckets")
    bucket = h % np.uint32(K)
    executor.note_spill_schedule("dedupe", buckets=K)

    # bucketed merge on the motion pipeline (exec/motionpipe.py): bucket
    # k+1's host subset build overlaps bucket k's device program
    from greengage_tpu.exec import motionpipe as _motionpipe

    run_bkts = [b for b in range(K) if (bucket == b).any()]

    def _bstage(bkt, _i):
        m = bucket == bkt
        sub_cols = {k: np.asarray(v)[m] for k, v in aux_cols.items()}
        sub_valids = {k: (np.asarray(v, bool)[m] if v is not None else None)
                      for k, v in aux_valids.items()}
        return sub_cols, sub_valids

    def _bcompute(staged, _bkt, _i):
        sub_cols, sub_valids = staged
        return executor.run_single(
            bucket_plan, consts, state_cols, raw=True,
            aux_tables={aux_name: (sub_cols, sub_valids)}, no_direct=True)

    bucket_results = _motionpipe.run_pipeline(
        run_bkts, _bstage, _bcompute, settings=executor.settings,
        label="dedupe")
    s_cols, s_valids = _collect_passes(state_cols, bucket_results)
    _charge_spill(s_cols, s_valids, "merge-buckets")
    aux2 = "@spill:partials2"
    host_scan = Scan(aux2, list(state_cols))
    host_scan.locus = outer_partial.locus
    host_scan.est_rows = float(len(next(iter(s_cols.values()), [])))
    final_plan = _replace_child(merged, outer_partial, host_scan)
    res = executor.run_single(
        final_plan, consts, out_cols, raw=raw,
        aux_tables={aux2: (s_cols, s_valids)}, no_direct=True)
    res.stats = dict(res.stats or {})
    res.stats["spill_merge_buckets"] = K
    return res, K


def _sortable_host_key(arr: np.ndarray, valid, desc: bool,
                       nulls_first: bool):
    """-> list of numpy arrays (minor->major within this key) whose
    ascending np.lexsort order equals the engine's order for this key.
    None when the host representation does not order (raw surrogates)."""
    a = np.asarray(arr)
    if a.dtype.kind in ("i", "u", "b"):
        enc = a.astype(np.int64)
        enc = (enc ^ np.int64(-0x8000000000000000)).astype(np.uint64)
        if desc:
            enc = ~enc
    elif a.dtype.kind == "f":
        bits = a.astype(np.float64).view(np.uint64)
        enc = np.where(bits >> np.uint64(63),
                       ~bits, bits | np.uint64(1 << 63))
        if desc:
            enc = ~enc
    elif a.dtype.kind in ("U", "S"):
        # C-locale string order == the dictionary rank order the device
        # sorts by; numpy cannot complement strings, so DESC strings use
        # a negated RANK over the merged domain instead
        uniq, inv = np.unique(a, return_inverse=True)
        enc = inv.astype(np.int64)
        if desc:
            enc = -enc
        enc = (enc ^ np.int64(-0x8000000000000000)).astype(np.uint64)
    else:
        return None
    nul = (np.zeros(len(a), np.uint8) if valid is None
           else (~np.asarray(valid, bool)).astype(np.uint8))
    if nulls_first:
        nul = 1 - nul
    # major key: null class; minor: encoded value (lexsort order)
    return [enc, nul]


def _host_sort_spec(sort: Sort, out_cols) -> list[tuple]:
    """Validate a Sort's keys as host-mergeable gathered output columns
    -> [(col id, desc, nulls_first)]; raises NotSpillable otherwise.
    Shared by the external-merge sort spill and the window spill's final
    host ordering — any key type must be known host-orderable BEFORE
    paying the pass loop."""
    by_id = {c.id: c for c in out_cols}
    keyspec = []
    for e, desc, nf in sort.keys:
        if not isinstance(e, E.ColRef) or e.name not in by_id:
            raise NotSpillable("sort key is not a gathered output column")
        kc = by_id[e.name]
        # raw TEXT arrives as int64 row surrogates whose numeric order is
        # row id, not string order
        if getattr(kc, "raw_ref", None) is not None \
                or getattr(kc, "raw_chain", None) is not None:
            raise NotSpillable("sort key is raw-encoded text")
        keyspec.append((e.name, bool(desc),
                        bool(desc) if nf is None else bool(nf)))
    return keyspec


def _host_lexsort(cols: dict, valids: dict, keyspec: list[tuple]):
    """One stable ascending lexsort over order-preserving key encodings
    (the k-way merge step); keys minor->major, so reverse the SQL key
    order and emit each key's (enc, null-class) pair in that order."""
    lex: list[np.ndarray] = []
    for name, desc, nf in reversed(keyspec):
        enc = _sortable_host_key(cols[name], valids[name], desc, nf)
        if enc is None:
            raise NotSpillable("sort key host representation does not order")
        lex.extend(enc)
    perm = np.lexsort(lex)
    cols = {k: v[perm] for k, v in cols.items()}
    valids = {k: (v[perm] if v is not None else None)
              for k, v in valids.items()}
    return cols, valids


def spill_sort_run(executor, plan: Motion, consts, out_cols, raw: bool,
                   instrument: bool = False):
    """External-merge sort spill (tuplesort.c role,
    /root/reference/src/backend/utils/sort/tuplesort.c:1): an ORDER BY
    whose input exceeds HBM runs as partitioned passes of the ORIGINAL
    plan — each pass sorts its chunk on device and arrives on the host
    already globally ordered (merge-sorted gather) — then the host merges
    the sorted runs with one stable lexsort over order-preserving key
    encodings (the k-way merge step, with host RAM as the workfile)."""
    if not isinstance(plan, Motion) or plan.kind is not MotionKind.GATHER:
        raise NotSpillable("sort spill needs a gathered result")
    node = plan.child
    limit_node = None
    if isinstance(node, Limit):
        limit_node = node
        node = node.child
    if not isinstance(node, Sort):
        raise NotSpillable("no sort at the gather point")
    sort = node
    keyspec = _host_sort_spec(sort, out_cols)
    candidates = [t for t in spill_candidate_tables(sort.child)
                  if not t.startswith("@") and count_scans(plan, t) == 1]
    if not candidates:
        raise NotSpillable("no partitionable table below the sort")
    # passes must NOT carry the Limit: its host re-limit would drop each
    # CHUNK's first `offset` rows; offset/limit apply once after the merge
    if limit_node is not None:
        pass_plan = copy.copy(plan)
        pass_plan.child = sort
    else:
        pass_plan = plan
    store = executor.store

    from greengage_tpu.exec.compile import Compiler
    from greengage_tpu.exec.executor import effective_limit_bytes

    settings = executor.settings
    limit_bytes = effective_limit_bytes(settings)
    candidates.sort(key=lambda t: -max(store.segment_rowcounts(t), default=0))
    cand = candidates[0]
    max_rows = max(store.segment_rowcounts(cand), default=0)
    if max_rows == 0:
        raise NotSpillable("empty partition candidate")
    floor = 1 << 12
    chunk = max_rows
    comp = None
    while True:
        chunk = max(chunk // 2, floor)
        comp = Compiler(executor.catalog, store, executor.mesh,
                        executor.nseg, consts, settings,
                        scan_cap_override={cand: chunk},
                        no_direct=True).compile(pass_plan)
        if comp.est_bytes <= limit_bytes * 0.7 or chunk == floor:
            break
    if comp.est_bytes > limit_bytes:
        raise NotSpillable("per-pass working set still exceeds the limit")
    npasses = -(-max_rows // chunk)
    if npasses > 256:
        raise NotSpillable(f"sort spill would need {npasses} passes (> 256)")
    executor.note_spill_schedule("sort", passes=npasses,
                                 chunks=[[cand, chunk, npasses]])

    from greengage_tpu.exec import staging as _staging
    from greengage_tpu.exec import workfile as _workfile

    prefetcher = _staging.PassPrefetcher(
        executor, comp.input_spec, store.manifest.snapshot())
    wf = _workfile.SpillWorkfile(executor, out_cols, "sorted-runs")
    try:
        try:
            for p in range(npasses):
                interrupt.check_interrupts()   # sorted-run pass boundary
                if p + 1 < npasses:
                    # warm the next sorted run's cold reads while this
                    # pass's device sort executes (same files, later row
                    # range)
                    prefetcher.kick()
                with _trace.span("spill-pass", cat="spill", index=p,
                                 total=npasses):
                    wf.add(executor.run_single(
                        pass_plan, consts, out_cols, raw=raw,
                        scan_cap_override={cand: chunk},
                        row_ranges={cand: (p * chunk, (p + 1) * chunk)},
                        no_direct=True, instrument=instrument))
        finally:
            prefetcher.close()

        cols, valids = wf.assemble()

        cols, valids = _host_lexsort(cols, valids, keyspec)
        if limit_node is not None:
            lo = limit_node.offset
            hi = None if limit_node.limit is None else lo + limit_node.limit
            cols = {k: v[lo:hi] for k, v in cols.items()}
            valids = {k: (v[lo:hi] if v is not None else None)
                      for k, v in valids.items()}

        from greengage_tpu.exec.executor import Result

        res = Result(columns=wf.columns, cols=cols, valids=valids,
                     _order=list(wf.order),
                     stats=dict(wf.base_stats or {}))
        res.stats["spill_kind"] = "sort"
        if instrument:
            # per-node rows sum across the sorted-run passes; the pass
            # plan's instrumented subtree IS the original plan's node
            # objects (the Limit, dropped from passes, stays
            # unannotated). Drop pass 0's counts inherited via base_stats
            # first — _merge_node_rows would otherwise double-count that
            # pass.
            res.stats.pop("node_rows", None)
            _merge_node_rows(res, wf.stats, {})
        return res, npasses
    finally:
        wf.close()


def _window_spill_point(plan: Motion):
    """-> (window, sort_node, limit_node) when the below-gather spine is
    [Limit?] [Sort?] [Project|Filter]* Window(partitioned) — the
    window-spill shape. None otherwise. Sort/Limit lift to the host
    merge (row order is the only thing they change); Project/Filter are
    row-wise and union-distributive, so they run inside every bucket."""
    node = plan.child
    sort_node = limit_node = None
    while isinstance(node, _WRAPPERS):
        if isinstance(node, Limit):
            if limit_node is not None or sort_node is not None:
                return None    # a Limit BELOW a Sort truncates pre-order
            limit_node = node
        elif isinstance(node, Sort):
            if sort_node is not None:
                return None
            sort_node = node
        node = node.child
    if not isinstance(node, Window) or getattr(node, "global_mode", False) \
            or not node.partition_keys:
        return None
    return node, sort_node, limit_node


def spill_window_run(executor, plan: Motion, consts, out_cols, raw: bool,
                     instrument: bool = False):
    """Window-partition spill: a window whose working set exceeds the
    admission limit completes by partitioning the PARTITION BY hash
    space into passes — exactly the DISTINCT spill's recursive-merge
    regime, but the bucketed unit is a whole window computation.

    Soundness: window functions depend ONLY on rows of their own
    partition, and a hash of the PARTITION BY keys puts every row of a
    partition in the same bucket — so running the window per disjoint
    bucket and unioning the outputs is exact (execHHashagg.c's batch
    partitioning, applied to nodeWindowAgg.c's input).

    Three phases:
      1. capture — chunked passes over the biggest base table(s) gather
         the window's INPUT rows (the subtree below its Redistribute) to
         the host: per-pass working set is chunk-sized (host RAM is the
         workfile);
      2. window passes — captured rows bucket by hash(PARTITION BY) % K;
         each bucket restages as an ephemeral host table, redistributes
         by the partition keys, and runs the window + its row-wise
         wrappers on device;
      3. finalize — any Sort above the window merges on the host over
         the unioned bucket outputs (the spill_sort_run lexsort), then
         LIMIT/OFFSET trims once."""
    settings = executor.settings
    if not bool(getattr(settings, "window_spill_enabled", True)):
        raise NotSpillable("window spill disabled (window_spill_enabled)")
    if not isinstance(plan, Motion) or plan.kind is not MotionKind.GATHER:
        raise NotSpillable("window spill needs a gathered result")
    point = _window_spill_point(plan)
    if point is None:
        raise NotSpillable("no partitioned window at the spill point")
    window, sort_node, limit_node = point
    if not all(isinstance(e, E.ColRef) for e in window.partition_keys):
        raise NotSpillable("window partition keys are not plain columns")
    keyspec = (_host_sort_spec(sort_node, out_cols)
               if sort_node is not None else None)
    child = window.child
    subtree = (child.child if isinstance(child, Motion)
               and child.kind is MotionKind.REDISTRIBUTE else child)
    sub_cols = []
    for c in subtree.out_cols():
        if getattr(c, "raw_ref", None) is not None \
                or getattr(c, "raw_chain", None) is not None:
            raise NotSpillable("window input carries raw-encoded text")
        # name == id: host staging maps aux columns by storage NAME
        sub_cols.append(ColInfo(c.id, c.type, c.id, c.dict_ref))
    sub_ids = {c.id for c in sub_cols}
    key_ids = [e.name for e in window.partition_keys]
    if not set(key_ids) <= sub_ids:
        raise NotSpillable("window partition keys are not captured "
                           "input columns")

    from greengage_tpu.exec import staging as _staging
    from greengage_tpu.exec import workfile as _workfile
    from greengage_tpu.exec.compile import Compiler
    from greengage_tpu.exec.executor import effective_limit_bytes

    limit_bytes = effective_limit_bytes(settings)
    store = executor.store

    # ---- phase 1: chunked capture of the window's input rows ---------
    capture = PartialState(subtree, sub_cols)
    capture.locus = subtree.locus
    capture.est_rows = subtree.est_rows
    pass_plan = Motion(MotionKind.GATHER, capture)
    pass_plan.locus = Locus.entry()
    candidates = [t for t in spill_candidate_tables(subtree)
                  if not t.startswith("@") and count_scans(plan, t) == 1]
    if not candidates:
        raise NotSpillable("no partitionable table below the window")
    chosen, per_table, nchunks, comp = _size_chunk_passes(
        executor, consts, pass_plan, candidates, limit_bytes)
    executor.note_spill_schedule(
        "window-capture", passes=nchunks,
        chunks=[[t, c, n] for t, c, n in per_table])
    grids = [[(t, (i * c, (i + 1) * c)) for i in range(n)]
             for t, c, n in per_table]
    caps = {t: c for t, c, _ in per_table}
    combos = list(itertools.product(*grids))
    prefetcher = _staging.PassPrefetcher(
        executor, comp.input_spec, store.manifest.snapshot())
    wf = _workfile.SpillWorkfile(executor, sub_cols, "window-input")
    try:
        try:
            for i, combo in enumerate(combos):
                interrupt.check_interrupts()   # spill pass boundary
                if i + 1 < len(combos):
                    prefetcher.kick()
                with _trace.span("spill-pass", cat="spill", index=i,
                                 total=len(combos), phase="capture"):
                    wf.add(executor.run_single(
                        pass_plan, consts, sub_cols, raw=True,
                        scan_cap_override=caps,
                        row_ranges=dict(combo), no_direct=True,
                        instrument=instrument))
        finally:
            prefetcher.close()
        aux_cols, aux_valids = wf.assemble()
    finally:
        wf.close()

    # ---- phase 2: window over PARTITION BY hash buckets --------------
    aux_name = "@spill:window"
    host_scan = Scan(aux_name, list(sub_cols))
    host_scan.locus = Locus.strewn(executor.nseg)
    host_scan.est_rows = float(len(next(iter(aux_cols.values()), [])))
    key_cols = {c.id: c for c in sub_cols}
    m = Motion(MotionKind.REDISTRIBUTE, host_scan,
               hash_exprs=[E.ColRef(k, key_cols[k].type) for k in key_ids])
    m.locus = Locus.hashed(tuple(key_ids), executor.nseg)
    m.est_rows = host_scan.est_rows
    node_map: dict = {}

    def rebuild(nd):
        if nd is window:
            w = copy.copy(window)
            node_map[id(w)] = id(window)
            w.child = m
            w.locus = m.locus
            return w
        if nd is sort_node or nd is limit_node:
            return rebuild(nd.child)
        clone = copy.copy(nd)
        node_map[id(clone)] = id(nd)
        clone.child = rebuild(nd.child)
        return clone

    bucket_plan = Motion(MotionKind.GATHER, rebuild(plan.child))
    bucket_plan.locus = Locus.entry()
    if bool(getattr(settings, "plan_validate", True)):
        # the bucket plan is a real plan: machine-check the spill shape
        # (hashed-on-partition-keys window, motion boundary) like any
        # other statement before paying K dispatches
        from greengage_tpu.analysis.plancheck import validate_plan

        validate_plan(bucket_plan, executor.catalog)

    h = _bucket_hash(aux_cols, aux_valids, key_ids)
    K = 1
    while True:
        mk = (h % np.uint32(max(K, 1))) == 0
        sub = {k: np.asarray(v)[mk] for k, v in aux_cols.items()}
        subv = {k: (np.asarray(v, bool)[mk] if v is not None else None)
                for k, v in aux_valids.items()}
        bcomp = Compiler(executor.catalog, store, executor.mesh,
                         executor.nseg, consts, settings,
                         aux_tables={aux_name: (sub, subv)},
                         no_direct=True).compile(bucket_plan)
        if bcomp.est_bytes <= max(limit_bytes, 1) * 0.9 or K >= 64:
            break
        K *= 2
    if bcomp.est_bytes > limit_bytes:
        raise NotSpillable(
            "per-bucket window working set still exceeds the limit at 64 "
            "partition buckets")
    bucket = h % np.uint32(K)
    executor.note_spill_schedule("window", buckets=K)

    # bucketed window passes on the motion pipeline (exec/motionpipe.py):
    # bucket k+1's host subset build + restage overlaps bucket k's device
    # program. Bucket 0 always runs (result schema base).
    from greengage_tpu.exec import motionpipe as _motionpipe

    run_bkts = [b for b in range(K) if b == 0 or (bucket == b).any()]

    def _bstage(bkt, _i):
        mk = bucket == bkt
        sub = {k: np.asarray(v)[mk] for k, v in aux_cols.items()}
        subv = {k: (np.asarray(v, bool)[mk] if v is not None else None)
                for k, v in aux_valids.items()}
        return sub, subv

    def _bcompute(staged, bkt, _i):
        sub, subv = staged
        with _trace.span("spill-pass", cat="spill", index=bkt, total=K,
                         phase="window"):
            return executor.run_single(
                bucket_plan, consts, out_cols, raw=raw,
                aux_tables={aux_name: (sub, subv)}, no_direct=True,
                instrument=instrument)

    bucket_results = _motionpipe.run_pipeline(
        run_bkts, _bstage, _bcompute, settings=settings, label="window")
    cols, valids = _collect_passes(out_cols, bucket_results)
    _charge_spill(cols, valids, "window-output")

    # ---- phase 3: host ordering + limit ------------------------------
    if keyspec is not None:
        cols, valids = _host_lexsort(cols, valids, keyspec)
    if limit_node is not None:
        lo = limit_node.offset
        hi = None if limit_node.limit is None else lo + limit_node.limit
        cols = {k: v[lo:hi] for k, v in cols.items()}
        valids = {k: (v[lo:hi] if v is not None else None)
                  for k, v in valids.items()}

    from greengage_tpu.exec.executor import Result

    base = bucket_results[0]
    res = Result(columns=base.columns, cols=cols, valids=valids,
                 _order=list(base._order), stats=dict(base.stats or {}))
    res.stats["spill_kind"] = "window"
    res.stats["spill_window_buckets"] = K
    if instrument:
        # per-node rows: capture passes share the ORIGINAL subtree's node
        # objects; bucket programs run clones remapped via node_map. Drop
        # bucket 0's counts inherited through base.stats first.
        res.stats.pop("node_rows", None)
        agg: dict = {}
        for st in wf.stats:
            for nid, nr in (((st or {}).get("node_rows")) or {}).items():
                agg[nid] = agg.get(nid, 0) + nr
        for r in bucket_results:
            for nid, nr in (((r.stats or {}).get("node_rows")) or {}).items():
                nid = node_map.get(nid, nid)
                agg[nid] = agg.get(nid, 0) + nr
        res.stats["node_rows"] = agg
    counters.inc("window_spill_runs")
    counters.inc("window_spill_passes", nchunks + K)
    return res, nchunks + K


def _replace_child(plan: Plan, target: Plan, repl: Plan,
                   node_map: dict | None = None) -> Plan:
    """Shallow-rebuild the path from ``plan`` to ``target`` with the target
    swapped (the original tree stays untouched for re-raising).
    ``node_map`` (optional) collects id(clone) -> id(original) for the
    cloned path nodes so instrumented row counts from the merged plan can
    be attributed back to the original tree's nodes."""

    if plan is target:
        return repl
    clone = copy.copy(plan)
    if node_map is not None:
        node_map[id(clone)] = id(plan)
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is None:
            continue
        if c is target or _contains(c, target):
            setattr(clone, attr, _replace_child(c, target, repl, node_map))
    return clone


def _contains(plan: Plan, target: Plan) -> bool:
    if plan is target:
        return True
    return any(_contains(c, target) for c in plan.children)
