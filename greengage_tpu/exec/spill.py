"""Host-offload spill: pass-partitioned execution past HBM capacity.

The workfile-manager role (reference: src/backend/utils/workfile_manager/
workfile_mgr.c:544, hybrid hash agg spilling in execHHashagg.c) rethought
for the TPU memory hierarchy: host RAM plays the workfile, and the unit of
spilling is a whole EXECUTION PASS instead of a hash batch.

Applicability: plans whose below-gather tree is
    [Sort|Limit|Project|Filter]* FinalAggregate( Motion( PartialAggregate(
        probe-linear subtree )))
— every TPC-H-style join+GROUP BY/scalar aggregate. The probe-linear
subtree is row-linear in one big table (joins only fan out on their PROBE
side; builds stay whole), so partitioning that table's rows into P chunks
and running the subtree + PARTIAL aggregate per chunk yields partial
states whose union merges exactly in the FINAL aggregate:

    pass p:  chunk_p -> joins -> partial agg   (fits in HBM)
             gather partial rows to host       (small)
    merge:   final plan with the partial subtree replaced by a host-staged
             input of all passes' partial rows

This completes any such query whose PER-PASS working set fits, instead of
rejecting it at the vmem admission check.
"""

from __future__ import annotations

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.planner.locus import Locus
from greengage_tpu.planner.logical import (Aggregate, ColInfo, Filter, Join,
                                           Limit, Motion, MotionKind,
                                           PartialState, Plan, Project, Scan,
                                           Sort)


class NotSpillable(ValueError):
    """The plan's shape cannot be pass-partitioned soundly."""


def partial_state_cols(partial: Aggregate) -> list:
    """ColInfos for a partial Aggregate's actual output: group keys plus
    the @c/@s/@m state columns the final phase merges (the compiler's
    partial-phase naming contract, exec/compile.py _c_aggregate)."""
    # keys re-exposed with name == id: the host-input staging maps columns
    # by storage NAME, and the ephemeral table's storage names are the ids
    out = [ColInfo(ci.id, ci.type, ci.id, ci.dict_ref)
           for ci, _ in partial.group_keys]
    for ci, a in partial.aggs:
        if a.func in ("count", "count_star"):
            out.append(ColInfo(ci.id + "@c", T.INT64, ci.id + "@c"))
        elif a.func == "sum":
            out.append(ColInfo(ci.id + "@s", a.type, ci.id + "@s"))
        elif a.func == "avg":
            stype = E.agg_result_type("sum", a.arg.type)
            out.append(ColInfo(ci.id + "@s", stype, ci.id + "@s"))
            out.append(ColInfo(ci.id + "@c", T.INT64, ci.id + "@c"))
        elif a.func in ("min", "max"):
            out.append(ColInfo(ci.id + "@m", a.arg.type, ci.id + "@m",
                               dict_ref=getattr(a.arg, "_dict_ref", None)))
    return out

_WRAPPERS = (Sort, Limit, Project, Filter)


def find_spill_split(plan: Motion):
    """-> (motion, partial_agg) of the topmost final/partial aggregate pair
    below the gather, or None if the plan does not have the spillable
    shape."""
    node = plan.child
    while isinstance(node, _WRAPPERS):
        node = node.child
    if not isinstance(node, Aggregate) or node.phase != "final":
        return None
    motion = node.child
    if not isinstance(motion, Motion):
        return None
    partial = motion.child
    if not isinstance(partial, Aggregate) or partial.phase != "partial":
        return None
    return motion, partial


def probe_lineage_tables(plan: Plan) -> list[str]:
    """Tables whose rows the subtree is LINEAR in: reachable from the root
    without crossing a join's build side (right child), a Union, or a
    Window (row-coupled)."""
    out = []
    node = plan
    while node is not None:
        if isinstance(node, Scan):
            out.append(node.table)
            return out
        if isinstance(node, Join):
            node = node.left
        elif isinstance(node, (Sort, Limit, Project, Filter, Motion)):
            # NOTE: a nested Aggregate (DISTINCT dedupe level) is NOT
            # row-linear — agg(chunk_A) U agg(chunk_B) != agg(all) — so it
            # ends the lineage and the plan is unspillable
            node = node.child
        else:
            return out
    return out


def count_scans(plan: Plan, table: str) -> int:
    n = 0
    stack = [plan]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan) and p.table == table:
            n += 1
        stack.extend(p.children)
    return n


def spill_run(executor, plan: Motion, consts, out_cols, raw: bool):
    """Execute ``plan`` in partitioned passes. Raises ValueError when the
    plan shape is not spillable (caller surfaces the vmem rejection)."""
    split = find_spill_split(plan)
    if split is None:
        raise NotSpillable("plan shape not spillable")
    motion, partial = split
    lineage = probe_lineage_tables(partial.child)
    if not lineage:
        raise NotSpillable("no probe-linear table to partition")
    table = lineage[-1]
    if table.startswith("@") or count_scans(plan, table) != 1:
        raise NotSpillable("partition table is scanned more than once")
    store = executor.store
    counts = store.segment_rowcounts(table)
    max_rows = max(counts, default=0)
    if max_rows == 0:
        raise NotSpillable("partition table is empty")

    from greengage_tpu.exec.executor import effective_limit_bytes

    settings = executor.settings
    limit_bytes = effective_limit_bytes(settings)

    # pass program: gather the PARTIAL aggregate's STATE columns (raw
    # storage representation; finalize must not decode)
    state_cols = partial_state_cols(partial)
    capture = PartialState(partial, state_cols)
    capture.locus = partial.locus
    capture.est_rows = partial.est_rows
    pass_plan = Motion(MotionKind.GATHER, capture)
    pass_plan.locus = Locus.entry()

    # find the chunk size that brings the pass program under the limit
    from greengage_tpu.exec.compile import Compiler

    chunk = max_rows
    floor = 1 << 12
    while True:
        chunk = max(chunk // 2, floor)
        comp = Compiler(executor.catalog, store, executor.mesh, executor.nseg,
                        consts, settings,
                        scan_cap_override={table: chunk}).compile(pass_plan)
        if comp.est_bytes <= limit_bytes * 0.7 or chunk == floor:
            break
    if comp.est_bytes > limit_bytes:
        raise NotSpillable("per-pass working set still exceeds the limit")
    npasses = -(-max_rows // chunk)

    # run the passes, collecting partial rows on the host (the workfile)
    partial_cols = state_cols
    host_cols = {c.id: [] for c in partial_cols}
    host_valids = {c.id: [] for c in partial_cols}
    any_invalid = {c.id: False for c in partial_cols}
    for p in range(npasses):
        rr = (p * chunk, (p + 1) * chunk)
        res = executor.run_single(
            pass_plan, consts, partial_cols, raw=True,
            scan_cap_override={table: chunk},
            row_ranges={table: rr})
        for c in partial_cols:
            host_cols[c.id].append(np.asarray(res.cols[c.id]))
            v = res.valids.get(c.id)
            if v is None:
                v = np.ones(len(res.cols[c.id]), dtype=bool)
            else:
                any_invalid[c.id] = True
            host_valids[c.id].append(np.asarray(v, bool))

    aux_cols = {c.id: np.concatenate(host_cols[c.id]) for c in partial_cols}
    aux_valids = {c.id: (np.concatenate(host_valids[c.id])
                         if any_invalid[c.id] else None)
                  for c in partial_cols}

    # merge program: the original plan with the partial subtree swapped for
    # a host input of the concatenated partial rows
    aux_name = "@spill:partials"
    host_scan = Scan(aux_name, list(partial_cols))
    host_scan.locus = partial.locus
    host_scan.est_rows = float(len(next(iter(aux_cols.values()), [])))
    merged = _replace_child(plan, partial, host_scan)
    return executor.run_single(
        merged, consts, out_cols, raw=raw,
        aux_tables={aux_name: (aux_cols, aux_valids)}), npasses


def _replace_child(plan: Plan, target: Plan, repl: Plan) -> Plan:
    """Shallow-rebuild the path from ``plan`` to ``target`` with the target
    swapped (the original tree stays untouched for re-raising)."""
    import copy

    if plan is target:
        return repl
    clone = copy.copy(plan)
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is None:
            continue
        if c is target or _contains(c, target):
            setattr(clone, attr, _replace_child(c, target, repl))
    return clone


def _contains(plan: Plan, target: Plan) -> bool:
    if plan is target:
        return True
    return any(_contains(c, target) for c in plan.children)
