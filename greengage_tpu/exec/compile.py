"""Physical compiler: planned tree -> one jitted SPMD program per query.

Where the reference interprets plans tuple-at-a-time per slice process
(ExecutorRun/ExecProcNode, src/backend/executor/execMain.c:1020), we compile
the ENTIRE plan below the top Gather Motion into a single function traced
under shard_map over the segment mesh: scans are padded device arrays,
Motions are collectives (parallel/motion.py), operators are the vectorized
kernels in ops/. XLA fuses across operator boundaries — the slice model
survives logically (Motion = slice boundary) but costs no process hop.

Static-shape policy (SURVEY.md §7 "hard parts"): all capacities derive from
storage manifests + planner estimates; kernels report overflow flags
(hash-table or motion-bucket exhaustion) and the executor re-compiles at the
next size tier — the spill/flow-control analog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
import hashlib
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.config import Settings
from greengage_tpu.ops import agg as agg_ops
from greengage_tpu.ops import hashing
from greengage_tpu.ops import join as join_ops
from greengage_tpu.ops import sort as sort_ops
from greengage_tpu.ops.batch import Batch
from greengage_tpu.ops.expr_eval import Evaluator
from greengage_tpu.parallel import SEG_AXIS
from greengage_tpu.parallel import motion as motion_ops
from greengage_tpu.planner.locus import LocusKind
from greengage_tpu.planner.logical import (
    Aggregate, ConstRel, Filter, Join, Limit, Motion, MotionKind, PartialState, Plan,
    Project, Scan, Sort, Union, Window,
)

VALID_PREFIX = "@v:"


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map with replication checking off, across jax versions:
    the top-level alias (and its check_vma flag) only exists on newer
    releases; older ones ship it as jax.experimental.shard_map with
    check_rep."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _pow2(n: float) -> int:
    m = 1
    while m < n:
        m <<= 1
    return m


def _lag_lead_lookup(fname, param, rn0, n_total, lookup, live):
    """lag/lead via a global position lookup -> (values, valid); the
    SQL-standard default argument replaces out-of-partition offsets.
    Shared by the ordered-global and range window kernels."""
    k, default = param if isinstance(param, tuple) else (param, None)
    p = rn0 - k if fname == "lag" else rn0 + k
    ok = (p >= 0) & (p < n_total)
    val, vv = lookup(p)
    if default is not None:
        val = jnp.where(ok, val, jnp.asarray(default, val.dtype))
        return val, (ok & vv | ~ok) & live
    return val, ok & vv & live


def _static_order_packable(keys, bounds) -> bool:
    """Compile-time mirror of ops/sort.order_pack_bits: the shared bounds
    budget (ops/sort.order_bounds_bits), plus no key may be TEXT (collation
    ranks via rank_lut are unpackable) or FLOAT64 (bounds come from integer
    ANALYZE stats only)."""
    from greengage_tpu.ops import sort as sort_ops

    if any(e.type.kind in (T.Kind.TEXT, T.Kind.FLOAT64)
           for e, _, _ in keys):
        return False
    return sort_ops.order_bounds_bits(bounds, len(keys)) is not None


@dataclass
class CompileResult:
    device_fn: object                  # jitted shard_map program
    input_spec: list                   # [(table, [storage cols], cap)]
    out_cols: list                     # ColInfo list of gather output
    flag_names: list[str]
    gather_child_locus: object
    merge_keys: list | None
    host_limit: tuple | None           # (limit, offset)
    capacity: int                      # below-gather output capacity
    metric_names: list[str] = field(default_factory=list)
    # overflow flag -> (plan node id, metric name): lets the executor size
    # the retry capacity from the exact cardinality the device reported
    flag_caps: dict = field(default_factory=dict)
    est_bytes: int = 0                 # rough per-segment device allocation
    node_rows: dict = field(default_factory=dict)  # metric -> plan node id
    flag_packs: dict = field(default_factory=dict)  # pack flag -> plan nid
    # True when the program may invoke the fused pallas dense-agg kernel:
    # the executor only treats a device failure as "pallas couldn't lower"
    # (and retries on the pure-XLA path) for such programs
    uses_fused: bool = False
    # hoisted-literal parameter slots, in slot order: the executor appends
    # one replicated (1,)-array per slot after the staged table inputs
    param_dtypes: tuple = ()
    # per-node slice of est_bytes (id(plan node) -> bytes, the same
    # identity node_rows uses) for the EXPLAIN ANALYZE per-node Memory
    # annotation
    node_est_bytes: dict = field(default_factory=dict)
    # measured memory accounting (runtime/memaccount.py), filled by the
    # executor at FIRST dispatch and reused on every warm program-cache
    # hit: the AOT-compiled executable (dispatch goes through it so the
    # program compiles exactly once), its memory_analysis dict, and a
    # don't-retry latch for backends where lower/compile/analyze fails.
    # mem_lock serializes the first analysis — two server threads cold-
    # dispatching the same cached program must not both pay the compile
    # (or double-count mem_analysis_runs)
    aot_fn: object = None
    mem_analysis: dict | None = None
    mem_failed: bool = False
    mem_lock: object = field(default_factory=threading.Lock)
    # vectorized serving (exec/batchserve.py): >0 means the program was
    # compiled with a leading member axis — parameters arrive stacked
    # (width, 1) per slot and every output/flag/metric carries a leading
    # (width,) axis the executor demuxes per member. 0 = classic program.
    batch_width: int = 0
    # feedback-store key this program reports measured bytes under
    # (batched programs qualify the statement key with the width bucket,
    # since est_bytes/measured bytes are width-scaled); set by the
    # executor at prepare time, read at dispatch
    fb_key: str | None = None


class Compiler:
    def __init__(self, catalog, store, mesh, nseg: int, consts: dict,
                 settings: Settings, tier: int = 0,
                 cap_overrides: dict | None = None, instrument: bool = False,
                 multihost: bool = False, scan_cap_override: dict | None = None,
                 aux_tables: dict | None = None,
                 pack_disabled: set | None = None,
                 fused_disabled: bool = False, no_direct: bool = False,
                 batch_width: int = 0):
        self.catalog = catalog
        self.store = store
        self.mesh = mesh
        self.nseg = nseg
        # own copy: the session caches the binder's consts dict across
        # executions, and compile stashes per-trace state (runtime param
        # tracers) into its view
        self.consts = dict(consts)
        # hoisted-literal vector (sql/paramize.py): values become traced
        # scalar inputs of the program, so the executable is value-generic
        self.params = self.consts.pop("@params@", None)
        self._consts_digest = self.consts.pop("@consts_digest@", None)
        self.s = settings
        self.tier = tier
        self.cap_overrides = cap_overrides or {}   # plan node id -> capacity
        self.flags: list[str] = []
        self.metrics: list[str] = []
        self.flag_caps: dict = {}
        # key packing from ANALYZE bounds: a bounds violation (stale stats)
        # re-runs the SAME tier with that node's packing disabled
        self.pack_disabled = pack_disabled or set()
        self.flag_packs: dict = {}         # pack flag id -> plan node id
        # fused dense-agg kernel: disabled wholesale after a pallas
        # compile failure (executor retries with the XLA path)
        self.fused_disabled = fused_disabled
        # spill passes force the general hash join: a direct-addressed
        # build allocates its FULL key domain regardless of how small the
        # chunked build scan is, defeating the pass-size search
        self.no_direct = no_direct
        self._reset_scan_state()
        self.instrument = instrument      # EXPLAIN ANALYZE per-node rows
        self.node_rows: dict[str, int] = {}   # metric name -> plan node id
        # multi-host: outputs/flags/metrics are device-reduced + replicated
        # so EVERY process fetches full results and takes identical
        # retry decisions (parallel/multihost.py lockstep invariants)
        self.multihost = multihost
        # spill support (exec/spill.py): chunked scan capacities and
        # host-staged ephemeral inputs ("@spill:" tables)
        self.scan_cap_override = scan_cap_override or {}
        self.aux_tables = aux_tables or {}
        # vectorized serving (exec/batchserve.py): wrap the per-member
        # program in a vmap over the stacked parameter inputs. Staged
        # table inputs are closed over (broadcast — every member scans the
        # same data); only parameters carry the member axis. Under
        # multihost the coordinator broadcasts the whole batch window
        # (op sql_batch) so every gang member compiles this same
        # width-bucketed program and its collectives rendezvous exactly
        # like a classic statement's.
        self.batch_width = int(batch_width)

    def _reset_scan_state(self) -> None:
        """Fresh per-walk scan collection: compile() re-resets so ONE
        Compiler can run shape_signature() and then compile() (the
        executor's miss path) without double-counting scan_count, which
        would silently disable single-scan zone pruning."""
        self.scan_caps: dict[str, int] = {}
        self.scan_cols: dict[str, set] = {}
        self.scan_direct: dict[str, int | None] = {}  # table -> pinned seg
        self.scan_count: dict[str, int] = {}
        self.scan_prune: dict[str, tuple] = {}        # table -> pushed preds
        self.scan_parts: dict[str, tuple | None] = {}  # table -> child tables
        self.scan_dyn: dict[str, tuple | None] = {}   # table -> dyn prune src

    def _merge_unpinned_scan_caps(self) -> None:
        """No (consistent) direct pin: the staged capacity must cover EVERY
        segment, not just the pinned ones two conflicting point-scans named
        (their caps were merged into scan_caps). Runs in BOTH compile() and
        shape_signature() so the signature digests the same post-merge caps
        the trace allocates — otherwise DML growing a NON-pinned segment
        past its bucket could leave the signature equal and reuse a
        too-small executable."""
        for t in sorted(self.scan_caps):
            if self.scan_direct.get(t) is None and t not in self.aux_tables:
                counts = self._seg_counts(t, self.scan_parts.get(t))
                self.scan_caps[t] = max(
                    self.scan_caps[t],
                    self._bucket_cap(t, max(counts, default=0)))

    # ------------------------------------------------------------------
    def compile(self, plan: Motion) -> CompileResult:
        assert isinstance(plan, Motion) and plan.kind is MotionKind.GATHER
        self._reset_scan_state()
        # Stable plan-node identity: preorder ordinals over the plan tree.
        # cap_overrides / pack_disabled / flag_caps / flag_packs cross
        # compile invocations through the executor's retry loop and plan
        # cache, where the SAME statement may be re-planned into fresh node
        # objects — id() would dangle (advisor r3), ordinals are stable
        # because re-planning the same statement is deterministic.
        self._nids: dict[int, int] = {}
        stack = [plan]
        while stack:
            p = stack.pop()
            self._nids[id(p)] = len(self._nids)
            stack.extend(reversed(p.children))
        self.uses_fused = False
        below = plan.child
        self._dict_refs: dict[str, tuple] = {}
        _collect_dict_refs(plan, self._dict_refs)
        # host-side limit/merge bookkeeping: ONLY the Limit directly below
        # the gather gets its OFFSET trimmed on the host; buried Limits must
        # drop their offset prefix on device (_c_limit)
        host_limit = None
        self._host_limit_node = None
        node = below
        if isinstance(node, Limit):
            host_limit = (node.limit, node.offset)
            self._host_limit_node = id(node)

        self._collect_scans(below)
        self._merge_unpinned_scan_caps()
        input_spec = []
        for t in sorted(self.scan_caps):
            cols = []
            for c in sorted(self.scan_cols[t]):
                cols.append(c)
                if t in self.aux_tables:
                    if self.aux_tables[t][1].get(c) is not None:
                        cols.append(VALID_PREFIX + c)
                elif self.store.has_nulls(t, c):
                    cols.append(VALID_PREFIX + c)
            # zone-map pruning applies only when this table is scanned once
            # (a second scan would need the pruned-away rows) and carries
            # no raw-text surrogates (their row numbering must stay whole)
            prune = self.scan_prune.get(t) or None
            if prune and (self.scan_count.get(t, 0) != 1 or any(
                    c.startswith(("@hp:", "@rc:", "@rp:", "@rl:", "@rw:"))
                    for c in cols)):
                prune = None
            if prune:
                schema_t = self.catalog.get(t)
                if any(col.type.kind == T.Kind.TEXT and col.encoding == "raw"
                       for col in schema_t.columns if col.name in self.scan_cols[t]):
                    prune = None
            dyn = self.scan_dyn.get(t)
            if not isinstance(dyn, tuple):
                dyn = None
            input_spec.append((t, cols, self.scan_caps[t],
                               self.scan_direct.get(t), prune,
                               self.scan_parts.get(t), dyn))

        compiled = self._compile_node(below)   # closure: ctx -> Batch
        out_cols = below.out_cols()

        # Device-side result compaction before the Gather (Gather Motion,
        # nodeMotion.c:171): the device->host relay costs ~65ms + 28MB/s
        # (NOTES.md), so shipping nseg x capacity padded rows for a
        # selective result is pathological. When estimated live rows sit
        # far below capacity, stable-sort live-first (2 operands) and ship
        # a small static slice; the exact live count feeds the overflow
        # retry. Sorts/Limits already compact; Aggregate outputs are dense
        # domains or group tables numbered live-first.
        cap_below = self._capacity_of(below)
        compact_k = self._gather_compact_k(plan, below)
        fid_cmp = mid_cmp = None
        if compact_k is not None:
            fid_cmp = f"gather_compact_overflow_{len(self.flags)}"
            self.flags.append(fid_cmp)
            mid_cmp = f"gather_compact_total_{len(self.metrics)}"
            self.metrics.append(mid_cmp)
            self.flag_caps[fid_cmp] = (self._nid(plan), mid_cmp)

        flag_names = list(self.flags)
        nseg = self.nseg

        mh = self.multihost
        metric_names = list(self.metrics)
        # hoisted-literal parameters (sql/paramize.py): one replicated
        # (1,)-scalar input per slot, read by Evaluator._eval_param — the
        # executable stays value-generic, values bind per dispatch
        param_dtypes = ()
        if self.params is not None and self.params.values:
            param_dtypes = tuple(t.np_dtype for t in self.params.types)
        nparams = len(param_dtypes)

        def seg_fn(*flat):
            from jax import lax

            ctx = {"tables": {}, "flags": []}
            i = 0
            for tname, cols, cap, _direct, _prune, _parts, _dyn in input_spec:
                entry = {}
                for c in cols:
                    entry[c] = flat[i]
                    i += 1
                entry["@present"] = flat[i]
                i += 1
                ctx["tables"][tname] = entry
            if nparams:
                # visible to every Evaluator(b, self.consts) in the
                # compiled closures; self.consts is this Compiler's copy,
                # so the tracers never leak into the session's cached pool
                self.consts["@params@rt"] = {
                    k: flat[i + k] for k in range(nparams)}
                i += nparams
            ctx["metrics"] = []
            batch = compiled(ctx)
            sel = batch.selection()
            if compact_k is not None:
                dead = (~sel).astype(jnp.uint8)
                rid = jnp.arange(sel.shape[0], dtype=jnp.int32)
                _, perm = lax.sort((dead, rid), num_keys=2)
                perm = perm[:compact_k]
                total = jnp.sum(sel.astype(jnp.int32))
                ctx["flags"].append((fid_cmp, total > compact_k))
                ctx["metrics"].append((mid_cmp, total))
                batch = Batch(
                    {c.id: batch.cols[c.id][perm] for c in out_cols},
                    {c.id: batch.valids[c.id][perm] for c in out_cols
                     if batch.valids.get(c.id) is not None},
                    jnp.arange(compact_k, dtype=jnp.int32) < total)
                sel = batch.selection()
            outs = []
            for c in out_cols:
                outs.append(batch.cols[c.id])
                v = batch.valids.get(c.id)
                outs.append(jnp.ones_like(sel) if v is None else v)
            outs.append(sel)
            if mh:
                # gather every segment's shard on device so all processes
                # hold the full result (the Gather Motion as a collective)
                outs = [lax.all_gather(o, SEG_AXIS) for o in outs]
            # emit in REGISTRATION order (flag_names/metric_names) — the
            # executor zips values against those name lists, and operators
            # may append to ctx in a different order than they registered
            fdict = dict(ctx["flags"])
            assert len(fdict) == len(flag_names), (
                sorted(fdict), sorted(flag_names))
            for name in flag_names:
                f = fdict[name].astype(jnp.int32)
                if mh:
                    f = lax.pmax(f, SEG_AXIS)
                outs.append(jnp.broadcast_to(f, (1,)))
            mdict = dict(ctx["metrics"])
            for name in metric_names:
                m = mdict[name].astype(jnp.int64)
                if mh:
                    m = (lax.psum(m, SEG_AXIS) if name.startswith("nrows_")
                         else lax.pmax(m, SEG_AXIS))
                outs.append(jnp.broadcast_to(m, (1,)))
            return tuple(outs)

        # vectorized serving (docs/PERF.md "Vectorized serving"): the same
        # per-member program body, vmapped over the stacked parameter
        # inputs — each slot arrives (width, 1) and every member instance
        # sees the classic (1,) contract. Table inputs are closed over
        # (broadcast: every member scans the same staged data); outputs,
        # flags, and metrics gain a leading (width,) member axis the
        # executor demuxes. Kept as a SEPARATE closure so the classic
        # program's jaxpr — and its persistent-XLA-cache entries — stay
        # byte-identical when batching is off.
        W = self.batch_width

        def seg_fn_batched(*flat):
            from jax import lax

            tables = {}
            i = 0
            for tname, cols, cap, _direct, _prune, _parts, _dyn in input_spec:
                entry = {}
                for c in cols:
                    entry[c] = flat[i]
                    i += 1
                entry["@present"] = flat[i]
                i += 1
                tables[tname] = entry
            pstack = flat[i:i + nparams]    # each (W, 1)

            def one_member(pflat):
                ctx = {"tables": dict(tables), "flags": [], "metrics": []}
                self.consts["@params@rt"] = {
                    k: pflat[k] for k in range(nparams)}
                batch = compiled(ctx)
                sel = batch.selection()
                if compact_k is not None:
                    dead = (~sel).astype(jnp.uint8)
                    rid = jnp.arange(sel.shape[0], dtype=jnp.int32)
                    _, perm = lax.sort((dead, rid), num_keys=2)
                    perm = perm[:compact_k]
                    total = jnp.sum(sel.astype(jnp.int32))
                    ctx["flags"].append((fid_cmp, total > compact_k))
                    ctx["metrics"].append((mid_cmp, total))
                    batch = Batch(
                        {c.id: batch.cols[c.id][perm] for c in out_cols},
                        {c.id: batch.valids[c.id][perm] for c in out_cols
                         if batch.valids.get(c.id) is not None},
                        jnp.arange(compact_k, dtype=jnp.int32) < total)
                    sel = batch.selection()
                outs = []
                for c in out_cols:
                    outs.append(batch.cols[c.id])
                    v = batch.valids.get(c.id)
                    outs.append(jnp.ones_like(sel) if v is None else v)
                outs.append(sel)
                fdict = dict(ctx["flags"])
                assert len(fdict) == len(flag_names), (
                    sorted(fdict), sorted(flag_names))
                for name in flag_names:
                    outs.append(jnp.broadcast_to(
                        fdict[name].astype(jnp.int32), (1,)))
                mdict = dict(ctx["metrics"])
                for name in metric_names:
                    outs.append(jnp.broadcast_to(
                        mdict[name].astype(jnp.int64), (1,)))
                return tuple(outs)

            return jax.vmap(one_member)(pstack)

        ncols_out = 2 * len(out_cols) + 1
        nouts = ncols_out + len(flag_names) + len(metric_names)
        if W:
            assert nparams, "a batched program needs parameter inputs"
            # outputs carry a leading member axis; segments concatenate
            # along axis 1 -> global (W, nseg * cap) per output
            out_specs = tuple([P(None, SEG_AXIS)] * nouts)
        elif mh:
            out_specs = tuple([P()] * nouts)
        else:
            out_specs = tuple([P(SEG_AXIS)] * nouts)
        fn = jax.jit(
            _shard_map(
                seg_fn_batched if W else seg_fn,
                mesh=self.mesh,
                in_specs=tuple(P(SEG_AXIS) for _ in range(
                    sum(len(c) + 1 for _, c, *_ in input_spec)))
                + tuple(P() for _ in range(nparams)),
                out_specs=out_specs,
            )
        )
        return CompileResult(
            device_fn=fn,
            input_spec=input_spec,
            out_cols=out_cols,
            flag_names=flag_names,
            gather_child_locus=below.locus,
            merge_keys=plan.merge_keys,
            host_limit=host_limit,
            capacity=compact_k if compact_k is not None
            else self._capacity_of(below),
            metric_names=metric_names,
            flag_caps=dict(self.flag_caps),
            # a batched program holds ~one member's intermediates PER
            # member (vmap), while the staged scan args are shared; charge
            # the conservative width multiple — admission over-refusing a
            # wide batch only narrows it to serial execution, never fails
            est_bytes=self._estimate_bytes(below) * max(W, 1),
            node_est_bytes=dict(self.node_est_bytes),
            node_rows=dict(self.node_rows),
            flag_packs=dict(self.flag_packs),
            uses_fused=self.uses_fused,
            param_dtypes=param_dtypes,
            batch_width=W,
        )

    def _nid(self, plan) -> int:
        """Stable preorder ordinal of a plan node (see compile())."""
        return self._nids[id(plan)]

    def _gather_compact_k(self, plan, below) -> int | None:
        """Device-side result-compaction slot count before the Gather, or
        None when the result ships uncompacted (shared by compile() and
        shape_signature — the decision is part of the program's shape)."""
        cap_below = self._capacity_of(below)
        if isinstance(below, (Sort, Limit, Aggregate, PartialState)) \
                or cap_below < (1 << 14):
            return None
        est = max(getattr(below, "est_rows", 0.0) or 0.0, 1.0)
        if below.locus is not None and below.locus.is_partitioned \
                and self.nseg > 1:
            est /= self.nseg
        k = _pow2(int(est * 1.5) + 64) * (4 ** self.tier)
        if self._nid(plan) in self.cap_overrides:
            k = _pow2(int(self.cap_overrides[self._nid(plan)]))
        if k * 2 <= cap_below:
            return min(k, cap_below)
        return None

    # ------------------------------------------------------------------
    # shape signature: the executable-reuse key half (docs/PERF.md)
    # ------------------------------------------------------------------
    _SIG_SKIP_FIELDS = frozenset((
        # tree edges (walked explicitly) and estimate-only fields — the
        # estimates' influence on the program is via the BUCKETED
        # capacities, which the signature captures separately
        "child", "left", "right", "inputs", "est_rows", "expand_est",
        "locus", "parts_total", "index_hits",
    ))

    def shape_signature(self, plan: Motion, snapshot=None) -> str:
        """Digest of EVERYTHING the traced program reads at compile time:
        plan structure + expression trees (pinned literal values and Param
        slots included), pow2-bucketed per-node capacities, referenced
        dictionary contents (fingerprints), the binder's consts pool
        digest, parameter dtypes, and the codegen-relevant settings.

        Equal signature => compiling this plan would produce an identical
        XLA program, so the executor's program cache can reuse the
        compiled executable ACROSS manifest versions: a DML that stays
        inside every capacity bucket and grows no dictionary re-dispatches
        the hot executable instead of recompiling."""

        self._snap = snapshot
        self._nids = {}
        stack = [plan]
        while stack:
            p = stack.pop()
            self._nids[id(p)] = len(self._nids)
            stack.extend(reversed(p.children))
        below = plan.child
        self._dict_refs = {}
        _collect_dict_refs(plan, self._dict_refs)
        # node-identity marker compared against id(p) during this same
        # walk — never digested into the payload
        self._host_limit_node = (
            id(below) if isinstance(below, Limit) else None)  # gg:ok(tracer)
        self._collect_scans(below)
        self._merge_unpinned_scan_caps()
        nodes = []
        dict_refs: dict = dict(self._dict_refs)
        stack = [plan]
        while stack:
            p = stack.pop()
            stack.extend(reversed(p.children))
            fields = []
            for name, v in vars(p).items():
                if name in self._SIG_SKIP_FIELDS:
                    continue
                fields.append((name, repr(v)))
                _collect_value_dict_refs(v, dict_refs)
            try:
                cap = self._capacity_of(p)
            except NotImplementedError:
                cap = -1
            extra = []
            if isinstance(p, Join) and getattr(p, "multi", False) \
                    and p.kind in ("semi", "anti"):
                extra.append(self._join_multi_expand_cap(p))
            nodes.append((type(p).__name__,
                          p.locus.kind.name if p.locus is not None else None,
                          cap, tuple(extra), tuple(fields)))
        dicts = []
        for ref in sorted(set(dict_refs.values())):
            try:
                dicts.append((ref, self.store.dictionary(*ref).fingerprint()))
            except Exception:
                # unresolved ref (e.g. evicted transient raw dict): the
                # caller treats a failed signature as uncacheable
                raise LookupError(f"dictionary {ref} unavailable")
        s = self.s
        settings_sig = (self.nseg, self.multihost, self.tier,
                        self.fused_disabled, tuple(sorted(self.pack_disabled)),
                        self.no_direct) + self.codegen_settings_sig(s)
        pdtypes = ()
        if self.params is not None:
            pdtypes = tuple(str(t.np_dtype) for t in self.params.types)
        gather_k = self._gather_compact_k(plan, below)
        payload = repr((tuple(nodes), tuple(dicts), self._consts_digest,
                        pdtypes, gather_k, settings_sig))
        return hashlib.sha1(payload.encode()).hexdigest()

    @staticmethod
    def codegen_settings_sig(s) -> tuple:
        """Every Settings field shape_signature digests. The executor keys
        its per-dispatch signature memo on this same tuple, so a SET that
        changes codegen invalidates memoized signatures, never a stale
        executable lookup."""
        return (s.dense_group_limit, s.fused_dense_agg,
                s.fused_dense_min_rows, s.fused_dense_max_domain,
                s.fused_dense_max_scratch_mb, s.motion_capacity_slack,
                s.motion_pipeline_buckets,
                s.hash_num_probes, s.hash_table_min, s.hash_table_max)

    def _estimate_bytes(self, plan: Plan) -> int:
        """Rough per-segment device allocation for the whole program
        (vmem_tracker admission analog): every node's batch capacity times
        its column widths, summed over the tree. Records the per-node
        slices in ``node_est_bytes`` (same id(node) identity as node_rows)
        so EXPLAIN ANALYZE can print a per-node Memory annotation."""
        total = 0
        self.node_est_bytes: dict[int, int] = {}
        stack = [plan]
        while stack:
            p = stack.pop()
            try:
                cap = self._capacity_of(p)
            except NotImplementedError:
                cap = 0
            width = sum(max(c.type.np_dtype.itemsize, 1) + 1 for c in p.out_cols())
            node_bytes = cap * width
            if isinstance(p, Window) \
                    and getattr(p, "global_mode", False) in ("ordered",
                                                             "range"):
                # all-gathered sorted key runs [nseg, cap] (8B keys) plus
                # one gathered (value, valid) run per positional function
                # argument — the real footprint of the gather-free path
                extra = cap * self.nseg * 9
                for _ci, fname, arg, _o, _pp in p.wfuncs:
                    if fname in ("lag", "lead", "first_value",
                                 "last_value") and arg is not None:
                        extra += cap * self.nseg * (
                            max(arg.type.np_dtype.itemsize, 1) + 1)
                node_bytes += extra
            if isinstance(p, Join):
                if getattr(p, "direct_domain", None) is not None \
                        and self.tier == 0 and not self.no_direct:
                    # dense build table: slot_row/counts int32 + int64 temps
                    node_bytes += int(p.direct_domain) * 16
                else:
                    try:
                        node_bytes += self._join_table_size(
                            self._capacity_of(p.right)) * 16
                    except NotImplementedError:
                        pass
            self.node_est_bytes[id(p)] = node_bytes
            total += node_bytes
            stack.extend(p.children)
        return total

    # ------------------------------------------------------------------
    # capacities
    # ------------------------------------------------------------------
    def _seg_counts(self, table: str, parts: tuple | None = None) -> list[int]:
        """Per-segment row counts, clamped by any spill chunk override.
        A partitioned scan sums its (pruned) child tables — pruning
        therefore shrinks the staged capacity, not just the IO."""
        snap = getattr(self, "_snap", None)
        if parts is not None:
            # one manifest snapshot for all children (it is a full-file
            # JSON parse; per-child reads would be O(parts) disk parses)
            snap = snap or self.store.manifest.snapshot()
            per = [self.store.segment_rowcounts(p, snap) for p in parts]
            counts = [sum(c[s] for c in per)
                      for s in range(self.nseg)] if per else [0] * self.nseg
        else:
            counts = self.store.segment_rowcounts(table, snap)
        cap = self.scan_cap_override.get(table)
        if cap is not None:
            counts = [min(c, cap) for c in counts]
        return counts

    def _bucket_cap(self, table: str, cap: int) -> int:
        """Round a scan capacity up to its pow2 bucket: a DML that stays
        within the bucket compiles to the SAME program shape, so the
        executor's executable cache survives manifest-version bumps
        (docs/PERF.md "plan cache"). Spill chunk overrides are exact pass
        boundaries — growing them would double-read rows across passes."""
        if table in self.scan_cap_override:
            return max(cap, 1)
        return _pow2(max(cap, 1))

    def _collect_scans(self, plan: Plan):
        if isinstance(plan, Scan):
            if plan.table in self.aux_tables:
                cols0 = self.aux_tables[plan.table][0]
                n = len(next(iter(cols0.values()))) if cols0 else 0
                cap = max(-(-max(n, 1) // self.nseg), 1)
                self.scan_caps[plan.table] = max(
                    self.scan_caps.get(plan.table, 0), cap)
                self.scan_cols.setdefault(plan.table, set()).update(
                    c.name for c in plan.cols)
                self.scan_direct[plan.table] = None
                self.scan_count[plan.table] = self.scan_count.get(plan.table, 0) + 1
                self.scan_prune[plan.table] = ()
                for c in plan.children:
                    self._collect_scans(c)
                return
            counts = self._seg_counts(plan.table, plan.parts)
            ds = plan.direct_seg
            if ds is not None and 0 <= ds < len(counts):
                cap = max(counts[ds], 1)
            else:
                cap = max(max(counts, default=0), 1)
            cap = self._bucket_cap(plan.table, cap)
            self.scan_caps[plan.table] = max(self.scan_caps.get(plan.table, 0), cap)
            self.scan_cols.setdefault(plan.table, set()).update(c.name for c in plan.cols)
            # direct dispatch only holds if EVERY scan of the table agrees
            prev = self.scan_direct.get(plan.table, "unset")
            self.scan_direct[plan.table] = ds if prev in ("unset", ds) else None
            self.scan_count[plan.table] = self.scan_count.get(plan.table, 0) + 1
            self.scan_prune[plan.table] = tuple(plan.prune_preds or ())
            # two scans of one parent stage the UNION of their live parts
            if plan.parts is not None:
                prev_parts = self.scan_parts.get(plan.table)
                merged = (tuple(dict.fromkeys((prev_parts or ()) + plan.parts))
                          if prev_parts is not None else plan.parts)
                self.scan_parts[plan.table] = merged
            else:
                self.scan_parts.setdefault(plan.table, None)
            # join-driven runtime pruning annotation; two scans with
            # different sources cannot share one prune — disable
            dyn = getattr(plan, "dyn_prune", None)
            prev_dyn = self.scan_dyn.get(plan.table, "unset")
            self.scan_dyn[plan.table] = (dyn if prev_dyn in ("unset", dyn)
                                         else None)
        for c in plan.children:
            self._collect_scans(c)

    def _capacity_of(self, plan: Plan) -> int:
        """Static per-segment row capacity of a node's output batch."""
        if isinstance(plan, ConstRel):
            return 1
        if isinstance(plan, Scan):
            if plan.table in self.scan_caps:
                return self.scan_caps[plan.table]
            return self._bucket_cap(
                plan.table,
                max(self._seg_counts(plan.table, plan.parts), default=0))
        if isinstance(plan, (Filter, Project, Sort, Window)):
            return self._capacity_of(plan.child)
        if isinstance(plan, Limit):
            cap = self._capacity_of(plan.child)
            if plan.limit is not None:
                return min(cap, plan.limit + plan.offset)
            return cap
        if isinstance(plan, Join):
            probe_cap = self._capacity_of(plan.left)
            if plan.kind == "cross":
                return probe_cap * max(self._capacity_of(plan.right), 1)
            if getattr(plan, "multi", False) and plan.kind in ("inner", "left"):
                if self._nid(plan) in self.cap_overrides:
                    # exact cardinality reported by the overflowed run
                    # (pow2 bucket: shape-stable across small DML)
                    return _pow2(max(int(self.cap_overrides[self._nid(plan)]),
                                     64))
                # CSR expansion output capacity from the (stats-driven)
                # cardinality estimate; est_rows is CLUSTER-GLOBAL, the
                # batch is per segment — divide by width for partitioned
                # loci (skew is caught by the exact-count overflow retry)
                est = max(plan.est_rows, 64.0) * 1.5
                if plan.locus is not None and plan.locus.is_partitioned \
                        and self.nseg > 1:
                    est /= self.nseg
                base = max(int(est) + 64, probe_cap // 4)
                return _pow2(base) * (4 ** self.tier)
            return probe_cap
        if isinstance(plan, Aggregate):
            if not plan.group_keys:
                return 1
            dense = self._dense_domains(plan)
            if dense is not None:
                d = 1
                for dom in dense:
                    d *= dom
                return d
            # sort-based path: output capacity = estimated group count with
            # slack; can never exceed the child batch (groups <= rows), and
            # an exact-count retry tightens it after overflow
            child_cap = self._capacity_of(plan.child)
            if self._nid(plan) in self.cap_overrides:
                return min(_pow2(max(int(self.cap_overrides[self._nid(plan)]),
                                     64)),
                           child_cap)
            est = int(max(plan.est_rows, 16.0) * 1.3) + 64
            return min(_pow2(est) * (4 ** self.tier), child_cap)
        if isinstance(plan, PartialState):
            return self._capacity_of(plan.child)
        if isinstance(plan, Union):
            return sum(self._capacity_of(c) for c in plan.inputs)
        if isinstance(plan, Motion):
            child_cap = self._capacity_of(plan.child)
            if plan.kind is MotionKind.BROADCAST:
                return child_cap * self.nseg
            if plan.kind is MotionKind.REDISTRIBUTE:
                return self.nseg * self._motion_bucket(child_cap)
            return child_cap
        raise NotImplementedError(type(plan).__name__)

    def _motion_bucket(self, child_cap: int) -> int:
        c = int(child_cap * self.s.motion_capacity_slack / self.nseg) + 64
        c = _pow2(c) * (4 ** self.tier)
        return min(c, child_cap)

    def _dense_domains(self, plan: Aggregate) -> list[int] | None:
        """Per-key dense domains (|dict|+1 / bool 3) when every group key has
        a known finite domain and the product fits the dense limit."""
        if not plan.group_keys:
            return None
        domains = []
        prod = 1
        for ci, e in plan.group_keys:
            if ci.type.kind is T.Kind.TEXT:
                d = getattr(e, "_dict_ref", None) or ci.dict_ref
                if d is None and isinstance(e, E.ColRef):
                    d = self._dict_refs.get(e.name)
                if d is None:
                    return None
                domains.append(len(self.store.dictionary(*d)) + 1)
            elif ci.type.kind is T.Kind.BOOL:
                domains.append(3)
            else:
                return None
            prod *= domains[-1]
            if prod > self.s.dense_group_limit:
                return None
        return domains

    def _join_table_size(self, build_cap: int) -> int:
        # 3x headroom keeps the load factor under ~1/3: expected chain ~1.5
        # rounds, and the dynamic-trip probe loop only pays what it walks
        m = _pow2(build_cap * 3) * (4 ** self.tier)
        return max(self.s.hash_table_min, min(m, self.s.hash_table_max))

    def _join_probes(self) -> int:
        return self.s.hash_num_probes * (2 ** min(self.tier, 2))

    # ------------------------------------------------------------------
    # node compilation (returns closures ctx -> Batch)
    # ------------------------------------------------------------------
    def _compile_node(self, plan: Plan):
        fn = getattr(self, "_c_" + type(plan).__name__.lower())(plan)
        if not self.instrument:
            # always-on row counters on Filter outputs: selectivity is the
            # estimate the planner gets most wrong, and one jnp.sum per
            # Filter is cheap enough to leave on for every normal run so
            # the feedback store sees actuals without EXPLAIN ANALYZE
            if isinstance(plan, Filter):
                mid = f"nrows_{len(self.metrics)}"
                self.metrics.append(mid)
                self.node_rows[mid] = id(plan)

                def counted_f(ctx):
                    b = fn(ctx)
                    ctx["metrics"].append(
                        (mid, jnp.sum(b.selection().astype(jnp.int64))))
                    return b

                return counted_f
            return fn
        # per-node output row counter (the INSTRUMENT_CDB / explain_gp.c
        # per-operator Instrumentation analog): one cheap reduction per node
        mid = f"nrows_{len(self.metrics)}"
        self.metrics.append(mid)
        self.node_rows[mid] = id(plan)

        def counted(ctx):
            b = fn(ctx)
            ctx["metrics"].append(
                (mid, jnp.sum(b.selection().astype(jnp.int64))))
            return b

        return counted

    def _c_constrel(self, plan):
        def run(ctx):
            from jax import lax

            sel = (lax.axis_index(SEG_AXIS) == 0)[None]   # [1], seg0 only
            return Batch({}, {}, sel)

        return run

    def _c_scan(self, plan: Scan):
        table = plan.table
        id_by_store = [(c.id, c.name) for c in plan.cols]

        def run(ctx):
            t = ctx["tables"][table]
            cols = {cid: t[sname] for cid, sname in id_by_store}
            valids = {
                cid: t[VALID_PREFIX + sname]
                for cid, sname in id_by_store
                if VALID_PREFIX + sname in t
            }
            return Batch(cols, valids, t["@present"])

        return run

    def _c_filter(self, plan: Filter):
        child = self._compile_node(plan.child)
        pred = plan.predicate

        def run(ctx):
            b = child(ctx)
            mask = Evaluator(b, self.consts).predicate(pred)
            return b.with_sel(b.selection() & mask)

        return run

    def _c_project(self, plan: Project):
        child = self._compile_node(plan.child)
        exprs = plan.exprs

        def run(ctx):
            b = child(ctx)
            ev = Evaluator(b, self.consts)
            cols, valids = {}, {}
            for ci, e in exprs:
                v, valid = ev.value(e)
                cols[ci.id] = v
                if valid is not None:
                    valids[ci.id] = valid
            return Batch(cols, valids, b.sel)

        return run

    # ---- joins ---------------------------------------------------------
    def _key_specs(self, batch: Batch, exprs):
        ev = Evaluator(batch, self.consts)
        specs = []
        for e in exprs:
            v, valid = ev.value(e)
            lut = None
            if e.type.kind is T.Kind.TEXT:
                d = getattr(e, "_dict_ref", None)
                if d is None and isinstance(e, E.ColRef):
                    d = self._dict_for_col(e.name)
                if d is not None:
                    lut = jnp.asarray(self.store.dictionary(*d).hashes())
            specs.append(agg_ops.KeySpec(v, valid, e.type, hash_lut=lut))
        return specs

    def _dict_for_col(self, col_id: str):
        return self._dict_refs.get(col_id)

    def _c_join_cross(self, plan: Join):
        """Cartesian pairing by repeat/tile index expansion — practical for
        the small (usually broadcast single-row ConstRel) build sides the
        planner produces; capacity = |L| x |B| keeps it honest under the
        vmem admission estimate for anything bigger."""
        left_fn = self._compile_node(plan.left)
        right_fn = self._compile_node(plan.right)
        Lcap = self._capacity_of(plan.left)
        Bcap = max(self._capacity_of(plan.right), 1)

        def run(ctx):
            lb = left_fn(ctx)
            rb = right_fn(ctx)
            li = jnp.repeat(jnp.arange(Lcap), Bcap)
            ri = jnp.tile(jnp.arange(Bcap), Lcap)
            cols = {cid: a[li] for cid, a in lb.cols.items()}
            cols.update({cid: a[ri] for cid, a in rb.cols.items()})
            valids = {cid: v[li] for cid, v in lb.valids.items()}
            valids.update({cid: v[ri] for cid, v in rb.valids.items()})
            sel = lb.selection()[li] & rb.selection()[ri]
            return Batch(cols, valids, sel)

        return run

    def _c_join(self, plan: Join):
        if plan.kind == "cross":
            return self._c_join_cross(plan)
        if getattr(plan, "multi", False):
            return self._c_join_multi(plan)
        left_fn = self._compile_node(plan.left)
        right_fn = self._compile_node(plan.right)
        build_cap = self._capacity_of(plan.right)
        M = self._join_table_size(build_cap)
        probes = self._join_probes()
        lkeys, rkeys = plan.left_keys, plan.right_keys
        kind = plan.kind
        residual = plan.residual
        fid_ov = f"join_overflow_{len(self.flags)}"
        self.flags.append(fid_ov)
        fid_dup = None
        if kind in ("inner", "left"):
            # semi/anti only need existence: duplicate build keys are fine
            fid_dup = f"join_dup_{len(self.flags)}"
            self.flags.append(fid_dup)
        right_cols = [c for c in plan.right.out_cols()]

        null_aware = getattr(plan, "null_aware", False)
        jkb = getattr(plan, "key_bounds", None)

        # direct addressing at tier 0 only: a build-overflow retry (stale
        # stats: live keys outside the analyzed domain) falls back to the
        # general hash table at tier 1
        direct = (getattr(plan, "direct_domain", None) is not None
                  and self.tier == 0 and len(rkeys) == 1
                  and not self.no_direct)
        direct_lo = getattr(plan, "direct_lo", 0)
        direct_domain = getattr(plan, "direct_domain", 0)
        fid_pack = None
        if (not direct and jkb is not None
                and self._nid(plan) not in self.pack_disabled
                and join_ops.join_pack_bits(jkb) is not None):
            fid_pack = f"pack_overflow_{len(self.flags)}"
            self.flags.append(fid_pack)
            self.flag_packs[fid_pack] = self._nid(plan)
        else:
            jkb = None

        def run(ctx):
            from jax import lax

            lb = left_fn(ctx)
            rb = right_fn(ctx)
            rspecs = self._key_specs(rb, rkeys)
            lspecs = self._key_specs(lb, lkeys)
            if direct:
                table = join_ops.build_direct(
                    rspecs[0], rb.selection(), direct_lo, direct_domain)
                matched, brow = join_ops.probe_direct(
                    table, lspecs[0], lb.selection(), direct_lo)
                walk_ov = jnp.zeros((), bool)
            else:
                table = join_ops.build(rspecs, rb.selection(), M, probes, jkb)
                matched, brow, walk_ov = join_ops.probe(
                    table, lspecs, lb.selection(), probes)
                if fid_pack is not None:
                    ctx["flags"].append((fid_pack, table.pack_viol))
            ctx["flags"].append((fid_ov, table.overflow | walk_ov))
            if fid_dup is not None:
                ctx["flags"].append((fid_dup, table.dup))
            cols = dict(lb.cols)
            valids = dict(lb.valids)
            sel = lb.selection()
            if kind == "inner":
                sel = sel & matched
            elif kind == "semi":
                sel = sel & matched
            elif kind == "anti" and null_aware:
                # NOT IN semantics: empty subquery -> everything qualifies;
                # otherwise NULL probe keys and any NULL subquery key
                # disqualify (result NULL -> filtered)
                rsel = rb.selection()
                def _gmax(b):
                    return lax.pmax(jnp.any(b).astype(jnp.int32), SEG_AXIS) > 0
                s_nonempty = _gmax(rsel)
                s_has_null = jnp.zeros((), bool)
                x_null = jnp.zeros_like(sel)
                for sp in rspecs:
                    if sp.valid is not None:
                        s_has_null = s_has_null | _gmax(rsel & ~sp.valid)
                for sp in lspecs:
                    if sp.valid is not None:
                        x_null = x_null | ~sp.valid
                qualify = jnp.where(s_nonempty,
                                    ~x_null & ~matched & ~s_has_null, True)
                sel = sel & qualify
            elif kind == "anti":
                sel = sel & ~matched
            if kind in ("inner", "left"):
                bcols = {c.id: rb.cols[c.id] for c in right_cols}
                bvalids = {c.id: rb.valids.get(c.id) for c in right_cols}
                g_cols, g_valids = join_ops.gather_build_columns(bcols, bvalids, brow, matched)
                cols.update(g_cols)
                valids.update(g_valids)
            out = Batch(cols, valids, sel)
            if residual is not None:
                mask = Evaluator(out, self.consts).predicate(residual)
                if kind == "left":
                    # residual only disqualifies the match, not the row
                    newm = matched & mask
                    for c in right_cols:
                        out.valids[c.id] = out.valids[c.id] & newm
                else:
                    out = out.with_sel(out.selection() & mask)
            return out

        return run

    def _join_multi_expand_cap(self, plan: Join) -> int:
        """Semi/anti multi-join pair-EXPANSION capacity: the output is
        probe-shaped (_capacity_of), but the matched-pair expansion needs
        its own slot count — the exact-total retry hint, else the
        planner's stats-driven pair estimate (|L||R|/NDV), else a blind
        multiple of the probe capacity. pow2-bucketed for shape-stable
        executable reuse (shape_signature walks this too)."""
        probe_cap0 = self._capacity_of(plan.left)
        if self._nid(plan) in self.cap_overrides:
            out_cap = _pow2(max(int(self.cap_overrides[self._nid(plan)]), 64))
        else:
            est = getattr(plan, "expand_est", None)
            if est:
                if plan.locus is not None and plan.locus.is_partitioned \
                        and self.nseg > 1:
                    est /= self.nseg
                out_cap = _pow2(int(est * 1.5) + 64)
            else:
                out_cap = _pow2(probe_cap0 * 2 + 64)
        return int(out_cap * (4 ** self.tier))

    def _c_join_multi(self, plan: Join):
        """Duplicate-capable join via CSR expansion: inner/left emit the
        matched pairs; semi/anti reduce the pairs back to PROBE rows with
        an any-match scatter — the shape EXISTS correlation with residual
        predicates needs (a probe row qualifies iff ANY duplicate build
        row passes equality AND the residual; nodeSubplan's hashed-EXISTS
        with non-hashable quals)."""
        left_fn = self._compile_node(plan.left)
        right_fn = self._compile_node(plan.right)
        build_cap = self._capacity_of(plan.right)
        M = self._join_table_size(build_cap)
        if plan.kind in ("semi", "anti"):
            out_cap = self._join_multi_expand_cap(plan)
        else:
            out_cap = self._capacity_of(plan)
        probes = self._join_probes()
        lkeys, rkeys = plan.left_keys, plan.right_keys
        kind = plan.kind
        residual = plan.residual
        fid_ov = f"join_overflow_{len(self.flags)}"
        self.flags.append(fid_ov)
        fid_exp = f"join_expand_overflow_{len(self.flags)}"
        self.flags.append(fid_exp)
        mid_total = f"join_expand_total_{len(self.metrics)}"
        self.metrics.append(mid_total)
        # overflow retry can size from the exact reported cardinality
        self.flag_caps[fid_exp] = (self._nid(plan), mid_total)
        left_cols = [c for c in plan.left.out_cols()]
        right_cols = [c for c in plan.right.out_cols()]
        jkb = getattr(plan, "key_bounds", None)
        fid_pack = None
        if (jkb is not None and self._nid(plan) not in self.pack_disabled
                and join_ops.join_pack_bits(jkb) is not None):
            fid_pack = f"pack_overflow_{len(self.flags)}"
            self.flags.append(fid_pack)
            self.flag_packs[fid_pack] = self._nid(plan)
        else:
            jkb = None

        def run(ctx):
            lb = left_fn(ctx)
            rb = right_fn(ctx)
            table = join_ops.build_multi(
                self._key_specs(rb, rkeys), rb.selection(), M, probes, jkb)
            (present, prow, brow, matched, expand_ov, walk_ov,
             total) = join_ops.probe_multi(
                table, self._key_specs(lb, lkeys), lb.selection(), probes,
                out_cap, left_outer=(kind == "left"))
            if fid_pack is not None:
                ctx["flags"].append((fid_pack, table.pack_viol))
            # walk overflow rides the table flag (tier retry grows M/hop
            # bound); expand overflow rides its own flag whose retry hint
            # sizes out_cap from `total`
            ctx["flags"].append((fid_ov, table.base.overflow | walk_ov))
            ctx["flags"].append((fid_exp, expand_ov))
            ctx["metrics"].append((mid_total, total))
            if kind in ("semi", "anti"):
                # evaluate the residual on the PAIR batch, then reduce to
                # per-probe-row existence
                keep = present & matched
                if residual is not None:
                    pcols, pvalids = {}, {}
                    for c in left_cols:
                        pcols[c.id] = lb.cols[c.id][prow]
                        v = lb.valids.get(c.id)
                        if v is not None:
                            pvalids[c.id] = v[prow]
                    for c in right_cols:
                        pcols[c.id] = rb.cols[c.id][brow]
                        v = rb.valids.get(c.id)
                        gv = v[brow] if v is not None else jnp.ones_like(matched)
                        pvalids[c.id] = gv & matched
                    pair = Batch(pcols, pvalids, keep)
                    keep = keep & Evaluator(pair, self.consts).predicate(residual)
                P = lb.selection().shape[0]
                any_kept = jnp.zeros((P + 1,), bool).at[
                    jnp.where(present, prow, P)].max(keep)[:P]
                lsel = lb.selection()
                sel2 = (lsel & any_kept if kind == "semi"
                        else lsel & ~any_kept)
                return Batch(dict(lb.cols), dict(lb.valids), sel2)
            cols, valids = {}, {}
            for c in left_cols:
                cols[c.id] = lb.cols[c.id][prow]
                v = lb.valids.get(c.id)
                if v is not None:
                    valids[c.id] = v[prow]
            for c in right_cols:
                cols[c.id] = rb.cols[c.id][brow]
                v = rb.valids.get(c.id)
                gv = v[brow] if v is not None else jnp.ones_like(matched)
                valids[c.id] = gv & matched
            sel = present if kind == "left" else (present & matched)
            out = Batch(cols, valids, sel)
            if residual is not None:
                mask = Evaluator(out, self.consts).predicate(residual)
                if kind == "left":
                    # per-match disqualification over duplicate builds
                    # (TPC-H Q13 shape): a pair failing the residual drops
                    # its output row — UNLESS the probe row then has no
                    # surviving pair, in which case its FIRST expanded row
                    # becomes the single null-extended row
                    keep = matched & mask
                    K = keep.shape[0]
                    P = lb.selection().shape[0]   # probe-side capacity
                    any_kept = jnp.zeros((P + 1,), bool).at[
                        jnp.where(present, prow, P)].max(keep)
                    first = jnp.concatenate(
                        [jnp.ones((min(K, 1),), bool), prow[1:] != prow[:-1]]) \
                        if K > 1 else jnp.ones((K,), bool)
                    null_row = present & first & ~any_kept[prow]
                    out = out.with_sel(present & (keep | null_row))
                    for c in right_cols:
                        out.valids[c.id] = out.valids[c.id] & keep
                else:
                    out = out.with_sel(out.selection() & mask)
            return out

        return run

    # ---- aggregation ---------------------------------------------------
    def _c_aggregate(self, plan: Aggregate):
        child_fn = self._compile_node(plan.child)
        dense = self._dense_domains(plan) if plan.group_keys else None
        use_sort = bool(plan.group_keys) and dense is None
        if dense is not None:
            M = 1
            for dom in dense:
                M *= dom
        else:
            M = 1
        child_cap = self._capacity_of(plan.child) if use_sort else None
        out_cap = self._capacity_of(plan) if use_sort else None
        fid = mid = None
        if use_sort and out_cap < child_cap:
            # output capacity below the theoretical max: group count can
            # overflow it; the device reports the exact count for the retry
            fid = f"agg_overflow_{len(self.flags)}"
            self.flags.append(fid)
            mid = f"agg_groups_{len(self.metrics)}"
            self.metrics.append(mid)
            self.flag_caps[fid] = (self._nid(plan), mid)
        keys = plan.group_keys
        aggs = plan.aggs
        phase = plan.phase
        # packed single-operand group sort from ANALYZE key bounds
        key_bounds = getattr(plan, "key_bounds", None)
        fid_pack = None
        if (use_sort and key_bounds is not None
                and self._nid(plan) not in self.pack_disabled
                and agg_ops.pack_bits(key_bounds) is not None):
            fid_pack = f"pack_overflow_{len(self.flags)}"
            self.flags.append(fid_pack)
            self.flag_packs[fid_pack] = self._nid(plan)
        else:
            key_bounds = None

        # fused single-pass dense kernel (ops/fused_agg.py): worth the
        # pallas call only on big batches; interpret mode keeps the CPU
        # mesh (tests/demo cluster) running the same code path. The kernel
        # unrolls D x n_accumulator masked reductions per grid step and
        # holds (n_acc, D, 128) x 8B VMEM scratch, so bound the group
        # domain and estimated scratch before committing to pallas
        # (advisor r3): past the bound the XLA path is the better program.
        n_acc_est = sum(2 if a.func == "avg" else 1 for _, a in aggs) + 1
        fused_ok = (dense is not None and not self.fused_disabled
                    and self.s.fused_dense_agg
                    and M <= self.s.fused_dense_max_domain
                    and n_acc_est * M * 128 * 8
                    <= self.s.fused_dense_max_scratch_mb << 20
                    and (self._capacity_of(plan.child)
                         >= self.s.fused_dense_min_rows))
        if fused_ok:
            self.uses_fused = True
        fused_interpret = self.mesh.devices.flat[0].platform == "cpu"

        def run(ctx):
            b = child_fn(ctx)
            sel = b.selection()
            gid = None
            perm = None
            used = None
            meta0 = {}
            cols, valids = {}, {}
            if keys and dense is not None:
                kspecs = self._key_specs(b, [e for _, e in keys])
                gid, _ = agg_ops.dense_gid(kspecs, dense, sel)
                decoded = agg_ops.dense_decode_keys(kspecs, dense, M)
                tkeys = [code for code, _ in decoded]
                tvalids = [valid for _, valid in decoded]
            elif keys:
                # sort-based high-cardinality grouping (execHHashagg spill
                # regime analog): sort by keys, cumsum-span reduce into the
                # group table; slot g's keys gather from its first row
                kspecs = self._key_specs(b, [e for _, e in keys])
                perm, boundary, sel_sorted, pack_viol = agg_ops.group_sort(
                    kspecs, sel, key_bounds)
                if fid_pack is not None:
                    ctx["flags"].append((fid_pack, pack_viol))
                tkeys, tvalids = [], []
            else:
                slots = jnp.where(sel, 0, 1)
                used = jnp.ones((1,), dtype=bool)
                tkeys, tvalids = [], []

            Mx = M
            ev = Evaluator(b, self.consts)
            for (ci, _), tk, tv in zip(keys, tkeys, tvalids):
                cols[ci.id] = tk
                if tv is not None:
                    valids[ci.id] = tv

            meta = {}

            def do_agg(specs):
                if gid is not None:
                    # "@used" rides the same pass: per-group live-row
                    # presence without the extra [n, D] broadcast scan
                    specs2 = list(specs) + [
                        agg_ops.AggSpec("@used", "count_star", None, None)]
                    from greengage_tpu.ops import fused_agg
                    if fused_ok and fused_agg.supported(specs2):
                        vals, avalids = fused_agg.fused_dense_aggregate(
                            gid, Mx, specs2, sel, interpret=fused_interpret)
                    else:
                        vals, avalids = agg_ops.dense_aggregate(
                            gid, Mx, specs2, sel)
                    meta0["used"] = vals.pop("@used") > 0
                    avalids.pop("@used", None)
                    return vals, avalids
                if perm is not None:
                    ps = [agg_ops.AggSpec(
                        s.name, s.func,
                        None if s.values is None else s.values[perm],
                        None if s.valid is None else s.valid[perm],
                        s.decimal_scale) for s in specs]
                    vals, avalids, meta["srcpos"], meta["total"] = \
                        agg_ops.sorted_group_aggregate(
                            boundary, sel_sorted, ps, out_cap)
                    return vals, avalids
                return agg_ops.aggregate(slots, Mx, specs, sel)

            if phase in ("single", "partial"):
                specs = []
                post = []   # (out id, kind, ...) finalization steps
                for ci, a in aggs:
                    arg_v, arg_valid, scale = None, None, 0
                    if a.arg is not None:
                        arg_v, arg_valid = ev.value(a.arg)
                        if a.arg.type.kind is T.Kind.DECIMAL:
                            scale = a.arg.type.scale
                    if phase == "single":
                        specs.append(agg_ops.AggSpec(ci.id, a.func, arg_v, arg_valid, scale))
                    else:
                        if a.func in ("count", "count_star"):
                            specs.append(agg_ops.AggSpec(ci.id + "@c", a.func, arg_v, arg_valid))
                        elif a.func == "sum":
                            specs.append(agg_ops.AggSpec(ci.id + "@s", "sum", arg_v, arg_valid))
                        elif a.func == "avg":
                            specs.append(agg_ops.AggSpec(ci.id + "@s", "sum", arg_v, arg_valid))
                            specs.append(agg_ops.AggSpec(ci.id + "@c", "count", arg_v, arg_valid))
                        elif a.func in ("min", "max"):
                            specs.append(agg_ops.AggSpec(ci.id + "@m", a.func, arg_v, arg_valid))
                vals, avalids = do_agg(specs)
                for name, v in vals.items():
                    cols[name] = v
                    if avalids.get(name) is not None:
                        valids[name] = avalids[name]
            else:  # final: merge partial states arriving in b
                specs = []
                finals = []
                for ci, a in aggs:
                    if a.func in ("count", "count_star"):
                        specs.append(agg_ops.AggSpec(
                            ci.id, "sum", b.cols[ci.id + "@c"], b.valids.get(ci.id + "@c")))
                        finals.append((ci, "count"))
                    elif a.func == "sum":
                        specs.append(agg_ops.AggSpec(
                            ci.id, "sum", b.cols[ci.id + "@s"], b.valids.get(ci.id + "@s")))
                        finals.append((ci, "sum"))
                    elif a.func == "avg":
                        specs.append(agg_ops.AggSpec(
                            ci.id + "@s", "sum", b.cols[ci.id + "@s"], b.valids.get(ci.id + "@s")))
                        specs.append(agg_ops.AggSpec(
                            ci.id + "@c", "sum", b.cols[ci.id + "@c"], b.valids.get(ci.id + "@c")))
                        scale = a.arg.type.scale if (a.arg is not None and
                                                     a.arg.type.kind is T.Kind.DECIMAL) else 0
                        finals.append((ci, "avg", scale))
                    elif a.func in ("min", "max"):
                        specs.append(agg_ops.AggSpec(
                            ci.id, a.func, b.cols[ci.id + "@m"], b.valids.get(ci.id + "@m")))
                        finals.append((ci, a.func))
                vals, avalids = do_agg(specs)
                for f in finals:
                    ci = f[0]
                    if f[1] == "avg":
                        s = vals[ci.id + "@s"].astype(jnp.float64)
                        c = vals[ci.id + "@c"].astype(jnp.float64)
                        res = s / jnp.where(c == 0, 1.0, c)
                        if f[2]:
                            res = res / (10.0 ** f[2])
                        cols[ci.id] = res
                        valids[ci.id] = vals[ci.id + "@c"] > 0
                    elif f[1] == "count":
                        cols[ci.id] = vals[ci.id].astype(jnp.int64)
                    else:
                        cols[ci.id] = vals[ci.id]
                        if avalids.get(ci.id) is not None:
                            valids[ci.id] = avalids[ci.id]
            if gid is not None:
                used = meta0["used"]
            if perm is not None:
                # group g's key values gather from its first sorted row
                rep = perm[meta["srcpos"]]
                for (ci, _), sp in zip(keys, kspecs):
                    cols[ci.id] = sp.values[rep]
                    if sp.valid is not None:
                        valids[ci.id] = sp.valid[rep]
                total = meta["total"]
                used = jnp.arange(out_cap, dtype=jnp.int32) < total
                if fid is not None:
                    # overflow reports the exact group count so the retry
                    # sizes itself right
                    ctx["flags"].append((fid, total > out_cap))
                    ctx["metrics"].append((mid, total.astype(jnp.int64)))
            return Batch(cols, valids, used)

        return run

    def _c_partialstate(self, plan: PartialState):
        return self._compile_node(plan.child)

    # ---- motion --------------------------------------------------------
    def _c_motion(self, plan: Motion):
        child_fn = self._compile_node(plan.child)
        if plan.kind is MotionKind.GATHER:
            raise AssertionError("nested gather")
        nseg = self.nseg
        if plan.kind is MotionKind.BROADCAST:
            def run(ctx):
                b = child_fn(ctx)
                arrs = dict(b.cols)
                for name, v in b.valids.items():
                    arrs[VALID_PREFIX + name] = v
                recv, precv = motion_ops.broadcast(arrs, b.selection())
                cols = {k: v for k, v in recv.items() if not k.startswith(VALID_PREFIX)}
                valids = {k[len(VALID_PREFIX):]: v for k, v in recv.items()
                          if k.startswith(VALID_PREFIX)}
                return Batch(cols, valids, precv)

            return run

        # REDISTRIBUTE
        child_cap = self._capacity_of(plan.child)
        C = self._motion_bucket(child_cap)
        # sub-exchange split (motion_pipeline_buckets): capacity is
        # pow2(>=64) x 4^tier, so any pow2 bucket count <= 64 divides it;
        # redistribute() itself guards the uneven case back to monolithic
        nb = max(int(getattr(self.s, "motion_pipeline_buckets", 1)), 1)
        hash_exprs = plan.hash_exprs
        fid = f"motion_overflow_{len(self.flags)}"
        self.flags.append(fid)

        if plan.range_spec is not None:
            # range repartition by sampled splitters (the distributed
            # sample-sort routing step): each segment samples S evenly
            # spaced values of its locally sorted keys, the gathered
            # sample sorts globally, and nseg-1 splitters route every row
            # so equal keys co-locate and segments own contiguous ranges.
            # Deterministic and SPMD-identical — every segment computes
            # the same splitters from the same all_gather.
            spec = plan.range_spec
            S = max(int(getattr(self.s, "window_range_sample", 64)), 8)

            def run_range(ctx):
                from jax import lax

                b = child_fn(ctx)
                sel = b.selection()
                ev = Evaluator(b, self.consts)
                v, valid = ev.value(spec["expr"])
                enc = sort_ops.encode_key64(v, spec["desc"], spec["kind"])
                MAXU = jnp.uint64(0xFFFFFFFFFFFFFFFF)
                if valid is not None:
                    # live NULL keys are all peers on the leading key:
                    # route them together to the end their placement puts
                    # them at
                    enc = jnp.where(valid, enc,
                                    jnp.uint64(0) if spec["nulls_first"]
                                    else MAXU)
                dead = ~sel
                n = sel.shape[0]
                enc_sorted = lax.sort(
                    (dead.astype(jnp.uint8), jnp.where(dead, MAXU, enc)),
                    num_keys=2)[1]
                live = jnp.sum((~dead).astype(jnp.int64))
                take = jnp.clip(
                    (jnp.arange(S, dtype=jnp.int64) * live) // S,
                    0, n - 1).astype(jnp.int32)
                samp = jnp.where(live > 0, enc_sorted[take], MAXU)
                g = lax.sort(
                    lax.all_gather(samp, SEG_AXIS).reshape(nseg * S))
                splitters = g[jnp.asarray(
                    [(i + 1) * (nseg * S) // nseg - 1
                     for i in range(nseg - 1)], dtype=jnp.int32)]
                # count of splitters strictly below enc: equal keys land
                # on the same destination segment, always
                dest = jnp.searchsorted(
                    splitters, enc, side="left").astype(jnp.int32)
                arrs = dict(b.cols)
                for name, vv in b.valids.items():
                    arrs[VALID_PREFIX + name] = vv
                recv, precv, overflow = motion_ops.redistribute(
                    arrs, sel, dest, nseg, C, nbuckets=nb)
                ctx["flags"].append((fid, overflow))
                cols = {k: a for k, a in recv.items()
                        if not k.startswith(VALID_PREFIX)}
                valids = {k[len(VALID_PREFIX):]: a for k, a in recv.items()
                          if k.startswith(VALID_PREFIX)}
                return Batch(cols, valids, precv)

            return run_range

        def run(ctx):
            b = child_fn(ctx)
            specs = self._key_specs(b, hash_exprs)
            h = hashing.row_hash([
                hashing.column_hash(s.values, s.valid, s.type, text_lut=s.hash_lut)
                for s in specs
            ])
            dest = hashing.segment_of(h, nseg)
            arrs = dict(b.cols)
            for name, v in b.valids.items():
                arrs[VALID_PREFIX + name] = v
            recv, precv, overflow = motion_ops.redistribute(
                arrs, b.selection(), dest, nseg, C, nbuckets=nb)
            ctx["flags"].append((fid, overflow))
            cols = {k: v for k, v in recv.items() if not k.startswith(VALID_PREFIX)}
            valids = {k[len(VALID_PREFIX):]: v for k, v in recv.items()
                      if k.startswith(VALID_PREFIX)}
            return Batch(cols, valids, precv)

        return run

    # ---- window --------------------------------------------------------
    def _c_window(self, plan: Window):
        from greengage_tpu.ops import window as win_ops

        if getattr(plan, "global_mode", False):
            return self._c_window_global(plan)
        child_fn = self._compile_node(plan.child)
        cap = self._capacity_of(plan.child)
        pkeys = plan.partition_keys
        okeys = plan.order_keys
        wfuncs = plan.wfuncs

        def run(ctx):
            b = child_fn(ctx)
            # sort by (partition, order); dead rows go to the end
            skeys = self._sort_keys(
                b, [(e, False, None) for e in pkeys] + list(okeys))
            perm, sel_sorted, _ = sort_ops.sort_batch(skeys, b.selection(), cap)
            cols, valids = sort_ops.apply_perm(b.cols, b.valids, perm)
            sb = Batch(cols, valids, sel_sorted)
            ev = Evaluator(sb, self.consts)

            def eq_prev(exprs):
                eq = jnp.ones((cap,), dtype=bool)
                for e in exprs:
                    v, valid = ev.value(e)
                    same = v[1:] == v[:-1]
                    if valid is not None:
                        same = (same & valid[1:] & valid[:-1]) | (
                            ~valid[1:] & ~valid[:-1])
                    eq = eq & jnp.concatenate(
                        [jnp.zeros((1,), bool), same])
                return eq

            part_eq = eq_prev(pkeys) if pkeys else jnp.concatenate(
                [jnp.zeros((1,), bool), jnp.ones((cap - 1,), bool)])
            # dead rows (parked at the end by the sort) must always BREAK
            # a group: padded buffer values can compare equal to the last
            # live row, silently extending its peer/partition end into
            # the dead region (ops/window.py documents both arrays False
            # at dead rows — enforce it)
            live_pair = sel_sorted & jnp.concatenate(
                [jnp.zeros((1,), bool), sel_sorted[:-1]])
            part_eq = part_eq & live_pair
            peer_eq = part_eq & (eq_prev([e for e, _, _ in okeys])
                                 if okeys else jnp.ones((cap,), bool))

            funcs = []
            for ci, fname, arg, ordered, param in wfuncs:
                vals, valid, scale = None, None, 0
                if arg is not None:
                    vals, valid = ev.value(arg)
                    if arg.type.kind is T.Kind.DECIMAL:
                        scale = arg.type.scale
                funcs.append(win_ops.WinFunc(ci.id, fname, vals, valid,
                                             scale, ordered, param))
            wvals, wvalids = win_ops.compute(part_eq, peer_eq, sel_sorted,
                                             funcs, frame=plan.frame)
            out_c = dict(sb.cols)
            out_v = dict(sb.valids)
            for ci, *_ in wfuncs:
                out_c[ci.id] = wvals[ci.id]
                if wvalids.get(ci.id) is not None:
                    out_v[ci.id] = wvalids[ci.id]
            return Batch(out_c, out_v, sel_sorted)

        return run

    def _c_window_global(self, plan: Window):
        """Distributed GLOBAL window (no PARTITION BY, no ORDER BY): the
        whole table is one partition, so every function reduces to a
        cross-mesh collective — rows never move (the planner previously
        funneled the entire table to one chip through a constant-key
        redistribute; VERDICT r3 weak #9). row_number() is the local
        live-row prefix count plus an exclusive scan of per-segment
        totals; sum/count/avg/min/max are psum/pmin/pmax of local
        partials broadcast back to every row."""
        child_fn = self._compile_node(plan.child)
        cap = self._capacity_of(plan.child)
        wfuncs = plan.wfuncs
        nseg = self.nseg
        if plan.global_mode == "ordered":
            return self._c_window_global_ordered(plan, child_fn, cap)
        if plan.global_mode == "range":
            return self._c_window_global_range(plan, child_fn, cap)

        def run(ctx):
            from jax import lax

            b = child_fn(ctx)
            sel = b.selection()
            ev = Evaluator(b, self.consts)
            seg = lax.axis_index(SEG_AXIS)
            out_c = dict(b.cols)
            out_v = dict(b.valids)
            for ci, fname, arg, _ordered, _param in wfuncs:
                vals = valid = None
                scale = 0
                if arg is not None:
                    vals, valid = ev.value(arg)
                    if arg.type.kind is T.Kind.DECIMAL:
                        scale = arg.type.scale
                lv = sel if valid is None else (sel & valid)
                if fname in ("first_value", "last_value"):
                    # whole-frame semantics (legal without ORDER BY, PG):
                    # the first/last live ROW of the one global partition
                    # in (segment, row) order — its value even when NULL
                    va = valid if valid is not None \
                        else jnp.ones((cap,), bool)
                    if fname == "first_value":
                        li = jnp.argmax(sel)
                    else:
                        li = cap - 1 - jnp.argmax(sel[::-1])
                    g_has = lax.all_gather(jnp.any(sel), SEG_AXIS)
                    g_val = lax.all_gather(vals[li], SEG_AXIS)
                    g_ok = lax.all_gather(va[li], SEG_AXIS)
                    if fname == "first_value":
                        pick = jnp.argmax(g_has)
                    else:
                        pick = nseg - 1 - jnp.argmax(g_has[::-1])
                    out_c[ci.id] = jnp.broadcast_to(g_val[pick], (cap,))
                    out_v[ci.id] = jnp.broadcast_to(
                        g_ok[pick] & jnp.any(g_has), (cap,))
                    continue
                if fname == "row_number":
                    local = jnp.cumsum(sel.astype(jnp.int64))
                    counts = lax.all_gather(
                        jnp.sum(sel.astype(jnp.int64)), SEG_AXIS)
                    offset = jnp.sum(jnp.where(
                        jnp.arange(nseg, dtype=jnp.int64) < seg, counts, 0))
                    out_c[ci.id] = local + offset
                    out_v.pop(ci.id, None)
                    continue
                if fname in ("count",):
                    total = lax.psum(jnp.sum(lv.astype(jnp.int64)), SEG_AXIS)
                    out_c[ci.id] = jnp.broadcast_to(total, (cap,))
                    out_v.pop(ci.id, None)
                    continue
                if fname in ("sum", "avg"):
                    acc = (jnp.float64 if vals.dtype.kind == "f"
                           else jnp.int64)
                    s = lax.psum(
                        jnp.sum(jnp.where(lv, vals.astype(acc), acc(0))),
                        SEG_AXIS)
                    c = lax.psum(jnp.sum(lv.astype(jnp.int64)), SEG_AXIS)
                    if fname == "sum":
                        out_c[ci.id] = jnp.broadcast_to(s, (cap,))
                    else:
                        a = (s.astype(jnp.float64)
                             / jnp.where(c == 0, 1, c).astype(jnp.float64))
                        if scale:
                            a = a / (10.0 ** scale)
                        out_c[ci.id] = jnp.broadcast_to(a, (cap,))
                    out_v[ci.id] = jnp.broadcast_to(c > 0, (cap,))
                    continue
                # min / max (same identity-fill rule as ops/window.py)
                if vals.dtype.kind == "f":
                    ident = jnp.array(jnp.inf if fname == "min" else -jnp.inf,
                                      vals.dtype)
                else:
                    info = jnp.iinfo(vals.dtype)
                    ident = jnp.array(info.max if fname == "min"
                                      else info.min, vals.dtype)
                filled = jnp.where(lv, vals, ident)
                red = jnp.min(filled) if fname == "min" else jnp.max(filled)
                glob = (lax.pmin(red, SEG_AXIS) if fname == "min"
                        else lax.pmax(red, SEG_AXIS))
                c = lax.psum(jnp.sum(lv.astype(jnp.int64)), SEG_AXIS)
                out_c[ci.id] = jnp.broadcast_to(glob, (cap,))
                out_v[ci.id] = jnp.broadcast_to(c > 0, (cap,))
            return Batch(out_c, out_v, sel)

        return run

    def _c_window_global_ordered(self, plan: Window, child_fn, cap: int):
        """Distributed GLOBAL ranking family (row_number/rank/dense_rank/
        ntile/lag/lead/first_value/last_value) over integer/date/decimal/
        float ORDER BY keys: each row's GLOBAL position and the global
        row count are computed IN PLACE — per segment, encode the keys
        order-preservingly into one uint64, locally sort, all_gather the
        sorted runs [nseg, cap] + live counts, and per row sum
        searchsorted counts across segments. ntile(k) is then arithmetic
        on (position, count); lag/lead/first/last resolve position ±
        offset via a lookup into the globally sorted gathered value runs.
        No funnel, no row motion: ~8B x rows of gathered keys (plus one
        value run per positional argument) vs moving every row AND its
        payload to one chip (reference shape: nodeWindowAgg.c over a
        distributed tuplesort).

        Encodings (planner._ordered_global_spec):
          packed — every key maps to (null_bit, value - lo) fields using
            EXACT zone-map bounds; DESC complements within the field,
            NULLS FIRST/LAST picks the null bit polarity. NULLs are
            ordinary key values here, so one code path serves all shapes.
          full64 — one key, no bounds: sign-flip encoding over the full
            64-bit domain; NULL keys form a separate runtime class
            counted via psum (all NULLs tie; placed per nulls_first).
        row_number() breaks ties deterministically by (segment, local
        sorted position); dense_rank counts distinct keys via a global
        two-key sort of the gathered runs + boundary cumsum."""
        from greengage_tpu.ops import window as win_ops

        wfuncs = plan.wfuncs
        nseg = self.nseg
        spec = plan.gkey_spec
        need_dense = any(f[1] == "dense_rank" for f in wfuncs)
        VALUE_FUNCS = ("lag", "lead", "first_value", "last_value")
        need_values = any(f[1] in VALUE_FUNCS for f in wfuncs)

        def run(ctx):
            from jax import lax

            b = child_fn(ctx)
            sel = b.selection()
            ev = Evaluator(b, self.consts)
            U1 = jnp.uint64(1)
            if spec["mode"] == "packed":
                shift = 64
                enc = jnp.zeros((cap,), jnp.uint64)
                for f in spec["fields"]:
                    v, valid = ev.value(f["expr"])
                    v64 = v.astype(jnp.int64)
                    ve = ((jnp.int64(f["hi"]) - v64) if f["desc"]
                          else (v64 - jnp.int64(f["lo"])))
                    # clamp defends against out-of-zone garbage at dead
                    # rows (fillers); live values are inside by soundness
                    ve = jnp.clip(ve, 0, (1 << f["bits"]) - 1).astype(jnp.uint64)
                    if valid is None:
                        # non-null bit: 1 under NULLS FIRST (nulls=0
                        # sort first), 0 under NULLS LAST
                        fe = ((U1 << jnp.uint64(f["bits"])) | ve
                              if f["nulls_first"] else ve)
                    else:
                        isnull = ~valid
                        nn_bit = U1 if f["nulls_first"] else jnp.uint64(0)
                        nl_bit = jnp.uint64(0) if f["nulls_first"] else U1
                        flag = jnp.where(isnull, nl_bit, nn_bit)
                        fe = (flag << jnp.uint64(f["bits"])) | jnp.where(
                            isnull, jnp.uint64(0), ve)
                    shift -= f["bits"] + 1
                    enc = enc | (fe << jnp.uint64(shift))
                isnull_cls = jnp.zeros((cap,), bool)
                nulls_first = False
                dead = ~sel
            else:                                   # full64, one key
                v, valid = ev.value(spec["expr"])
                enc = sort_ops.encode_key64(v, spec["desc"],
                                            spec.get("kind", "int"))
                isnull_cls = (sel & ~valid) if valid is not None \
                    else jnp.zeros((cap,), bool)
                nulls_first = spec["nulls_first"]
                dead = ~sel | isnull_cls

            # dead rows park at the top of the sorted run (dead flag is
            # the primary sort key) and their counted contributions are
            # clamped away by the live counts below
            enc_d = jnp.where(dead, jnp.uint64(0xFFFFFFFFFFFFFFFF), enc)
            rid = jnp.arange(cap, dtype=jnp.int32)
            _d, sorted_enc, sorted_rid = lax.sort(
                (dead.astype(jnp.uint8), enc_d, rid), num_keys=2,
                is_stable=True)
            live_n = jnp.sum((~dead).astype(jnp.int64))
            g_sorted = lax.all_gather(sorted_enc, SEG_AXIS)   # [nseg, cap]
            g_live = lax.all_gather(live_n, SEG_AXIS)         # [nseg]
            left = jax.vmap(
                lambda a: jnp.searchsorted(a, enc_d, side="left"))(g_sorted)
            right = jax.vmap(
                lambda a: jnp.searchsorted(a, enc_d, side="right"))(g_sorted)
            left = jnp.minimum(left, g_live[:, None])
            right = jnp.minimum(right, g_live[:, None])
            less_g = jnp.sum(left, axis=0)
            seg = lax.axis_index(SEG_AXIS)
            prior = jnp.arange(nseg)[:, None] < seg
            eq_prior = jnp.sum(jnp.where(prior, right - left, 0), axis=0)
            # local tie position (stable by original row order)
            pos = jnp.zeros((cap,), jnp.int32).at[sorted_rid].set(rid)
            first_eq = jnp.minimum(
                jnp.searchsorted(sorted_enc, enc_d, side="left"), live_n)
            local_eq_before = pos.astype(jnp.int64) - first_eq

            # NULL class (full64 only): all NULL-key rows tie; placed
            # before or after every valued row per nulls_first
            n_null_local = jnp.sum(isnull_cls.astype(jnp.int64))
            g_null = lax.all_gather(n_null_local, SEG_AXIS)   # [nseg]
            n_null_total = jnp.sum(g_null)
            total_valued = jnp.sum(g_live)
            null_prior_segs = jnp.sum(jnp.where(jnp.arange(nseg) < seg,
                                                g_null, 0))
            local_null_idx = jnp.cumsum(isnull_cls.astype(jnp.int64)) - 1
            valued_base = jnp.where(nulls_first, n_null_total, 0)
            null_base = jnp.where(nulls_first, 0, total_valued)

            # global 0-based position of every row (row_number semantics:
            # ties break by (segment, local sorted position)) and the
            # GLOBAL row count — ntile is pure arithmetic on these, and
            # lag/lead/first/last resolve position±offset via the lookup
            rn0 = jnp.where(
                isnull_cls,
                null_base + null_prior_segs + local_null_idx,
                valued_base + less_g + eq_prior + local_eq_before
            ).astype(jnp.int64)
            n_total = total_valued + n_null_total

            flat = flive = None
            if need_dense or need_values:
                flat = g_sorted.reshape(nseg * cap)
                flive = (jnp.arange(cap)[None, :] < g_live[:, None]) \
                    .reshape(nseg * cap)

            dense_b = total_distinct = None
            if need_dense:
                # distinct count: one global sort of the gathered runs by
                # (enc, live-first) + boundary flags on live key changes.
                # Dead entries carry 0xFF..FF; a LIVE max-value row sorts
                # before them (secondary key) so its boundary still counts
                s_enc, s_dead, s_live = lax.sort(
                    (flat, (~flive).astype(jnp.uint8), flive), num_keys=2,
                    is_stable=True)
                first = jnp.concatenate([
                    jnp.array([True]), s_enc[1:] != s_enc[:-1]])
                d = (s_live & first).astype(jnp.int64)
                cum_excl = jnp.cumsum(d) - d
                idx = jnp.searchsorted(s_enc, enc_d, side="left")
                dense_b = cum_excl[jnp.clip(idx, 0, nseg * cap - 1)]
                total_distinct = jnp.sum(d)

            cum_null = jnp.cumsum(g_null)

            def make_lookup(arg):
                """-> lookup(p): the window argument's (value, valid) at
                GLOBAL position p. Valued positions read the globally
                sorted gathered value run — live entries occupy exactly
                [0, total_valued) in rank order, and the stable sort's
                seg-major tie order equals the rank tie-break (runs are
                locally sorted, flattened segment-major). full64
                NULL-class positions read a (segment, row)-ordered
                gathered run of the null-key rows."""
                vals, valid = ev.value(arg)
                va = valid if valid is not None else jnp.ones((cap,), bool)
                g_vs = lax.all_gather(
                    vals[sorted_rid], SEG_AXIS).reshape(nseg * cap)
                g_vv = lax.all_gather(
                    va[sorted_rid], SEG_AXIS).reshape(nseg * cap)
                _e, _d2, s_vals, s_valid = lax.sort(
                    (flat, (~flive).astype(jnp.uint8), g_vs, g_vv),
                    num_keys=2, is_stable=True)
                if spec["mode"] == "full64":
                    npos = jnp.where(
                        isnull_cls,
                        jnp.cumsum(isnull_cls.astype(jnp.int32)) - 1,
                        jnp.int32(cap))
                    g_nv = lax.all_gather(
                        jnp.zeros((cap + 1,), vals.dtype)
                        .at[npos].set(vals)[:cap], SEG_AXIS)   # [nseg,cap]
                    g_nvv = lax.all_gather(
                        jnp.zeros((cap + 1,), bool)
                        .at[npos].set(va)[:cap], SEG_AXIS)
                else:
                    g_nv = g_nvv = None

                def lookup(p):
                    q = jnp.clip(
                        jnp.where(nulls_first, p - n_null_total, p),
                        0, nseg * cap - 1)
                    val = s_vals[q]
                    ok = s_valid[q]
                    if g_nv is not None:
                        in_null = (p < n_null_total) if nulls_first \
                            else (p >= total_valued)
                        j = p if nulls_first else p - total_valued
                        sg = jnp.clip(
                            jnp.searchsorted(cum_null, j, side="right"),
                            0, nseg - 1)
                        loc = jnp.clip(j - (cum_null[sg] - g_null[sg]),
                                       0, cap - 1).astype(jnp.int32)
                        val = jnp.where(in_null, g_nv[sg, loc], val)
                        ok = jnp.where(in_null, g_nvv[sg, loc], ok)
                    return val, ok

                return lookup

            out_c = dict(b.cols)
            out_v = dict(b.valids)
            for ci, fname, arg, _ordered, param in wfuncs:
                if fname == "row_number":
                    out_c[ci.id] = rn0 + 1
                    out_v.pop(ci.id, None)
                    continue
                if fname == "rank":
                    out_c[ci.id] = jnp.where(
                        isnull_cls, null_base, valued_base + less_g) + 1
                    out_v.pop(ci.id, None)
                    continue
                if fname == "dense_rank":
                    has_nulls_first = (n_null_total > 0) & nulls_first
                    valued = dense_b + has_nulls_first.astype(jnp.int64)
                    nullv = jnp.where(nulls_first, 0, total_distinct)
                    nullv = jnp.broadcast_to(nullv, (cap,))
                    out_c[ci.id] = jnp.where(isnull_cls, nullv, valued) + 1
                    out_v.pop(ci.id, None)
                    continue
                if fname == "ntile":
                    out_c[ci.id] = win_ops.ntile_bucket(rn0, n_total, param)
                    out_v.pop(ci.id, None)
                    continue
                if fname in ("lag", "lead"):
                    out_c[ci.id], out_v[ci.id] = _lag_lead_lookup(
                        fname, param, rn0, n_total, make_lookup(arg), sel)
                    continue
                # first_value / last_value, default frame (RANGE
                # UNBOUNDED PRECEDING..CURRENT ROW): frame start is the
                # global partition start, frame end the row's last PEER
                lk = make_lookup(arg)
                if fname == "first_value":
                    p = jnp.zeros((cap,), jnp.int64)
                else:
                    eq_total = jnp.sum(right - left, axis=0)
                    p = jnp.where(
                        isnull_cls,
                        null_base + n_null_total - 1,
                        valued_base + less_g + eq_total - 1
                    ).astype(jnp.int64)
                val, vv = lk(p)
                out_c[ci.id] = val
                out_v[ci.id] = vv & sel
            return Batch(out_c, out_v, sel)

        return run

    def _c_window_global_range(self, plan: Window, child_fn, cap: int):
        """Global window over RANGE-repartitioned rows (the child is the
        sampled-splitter Redistribute, _c_motion): each segment owns a
        contiguous range of the leading ORDER BY key with equal keys
        co-located, so after a segment-local sort by the FULL key list
        the global order is simply the concatenation of the per-segment
        runs — peer groups never straddle a boundary. Rank family and
        dense_rank stitch with all-gathered per-segment counts, ntile is
        arithmetic on (global position, global count), running
        sum/count/avg/min/max add prior segments' totals, and
        lag/lead/first_value resolve cross-segment positions via a
        lookup into the all-gathered sorted runs. One balanced
        Redistribute where the planner used to funnel every row to one
        chip."""
        from greengage_tpu.ops import window as win_ops

        wfuncs = plan.wfuncs
        nseg = self.nseg
        okeys = plan.order_keys

        def run(ctx):
            from jax import lax

            b = child_fn(ctx)
            skeys = self._sort_keys(b, okeys)
            perm, sel_sorted, _ = sort_ops.sort_batch(
                skeys, b.selection(), cap)
            cols, valids = sort_ops.apply_perm(b.cols, b.valids, perm)
            sb = Batch(cols, valids, sel_sorted)
            ev = Evaluator(sb, self.consts)
            idx = jnp.arange(cap, dtype=jnp.int32)
            # peer boundaries among LIVE rows (dead rows park at the end
            # and always break a group — padded values can tie)
            eq = jnp.ones((cap,), bool)
            for e, _d, _nf in okeys:
                v, valid = ev.value(e)
                same = v[1:] == v[:-1]
                if valid is not None:
                    same = (same & valid[1:] & valid[:-1]) | (
                        ~valid[1:] & ~valid[:-1])
                eq = eq & jnp.concatenate([jnp.zeros((1,), bool), same])
            eq = eq & sel_sorted & jnp.concatenate(
                [jnp.zeros((1,), bool), sel_sorted[:-1]])
            peer_bound = ~eq
            peer_start = win_ops._starts(peer_bound, idx)
            peer_end = jnp.clip(win_ops._ends(peer_start, cap), 0, cap - 1)

            n_live = jnp.sum(sel_sorted.astype(jnp.int64))
            g_n = lax.all_gather(n_live, SEG_AXIS)           # [nseg]
            seg = lax.axis_index(SEG_AXIS)
            prior_mask = jnp.arange(nseg) < seg
            prior = jnp.sum(jnp.where(prior_mask, g_n, 0))
            n_total = jnp.sum(g_n)
            cum_n = jnp.cumsum(g_n)
            # live rows occupy the local prefix, so local index == local
            # rank and the global 0-based position is one offset away
            rn0 = idx.astype(jnp.int64) + prior

            def make_lookup(vals, va):
                g_vals = lax.all_gather(vals, SEG_AXIS)      # [nseg, cap]
                g_valid = lax.all_gather(va, SEG_AXIS)

                def lookup(p):
                    sg = jnp.clip(
                        jnp.searchsorted(cum_n, p, side="right"),
                        0, nseg - 1)
                    loc = jnp.clip(p - (cum_n[sg] - g_n[sg]),
                                   0, cap - 1).astype(jnp.int32)
                    return g_vals[sg, loc], g_valid[sg, loc]

                return lookup

            out_c = dict(sb.cols)
            out_v = dict(sb.valids)
            db_loc = jnp.cumsum((peer_bound & sel_sorted).astype(jnp.int64))
            for ci, fname, arg, _ordered, param in wfuncs:
                vals = valid = None
                scale = 0
                if arg is not None:
                    vals, valid = ev.value(arg)
                    if arg.type.kind is T.Kind.DECIMAL:
                        scale = arg.type.scale
                if fname == "row_number":
                    out_c[ci.id] = rn0 + 1
                    out_v.pop(ci.id, None)
                    continue
                if fname == "rank":
                    out_c[ci.id] = peer_start.astype(jnp.int64) + prior + 1
                    out_v.pop(ci.id, None)
                    continue
                if fname == "dense_rank":
                    g_d = lax.all_gather(db_loc[cap - 1], SEG_AXIS)
                    out_c[ci.id] = db_loc + jnp.sum(
                        jnp.where(prior_mask, g_d, 0))
                    out_v.pop(ci.id, None)
                    continue
                if fname == "ntile":
                    out_c[ci.id] = win_ops.ntile_bucket(rn0, n_total, param)
                    out_v.pop(ci.id, None)
                    continue
                if fname in ("lag", "lead"):
                    va = valid if valid is not None \
                        else jnp.ones((cap,), bool)
                    out_c[ci.id], out_v[ci.id] = _lag_lead_lookup(
                        fname, param, rn0, n_total,
                        make_lookup(vals, va), sel_sorted)
                    continue
                if fname in ("first_value", "last_value"):
                    va = valid if valid is not None \
                        else jnp.ones((cap,), bool)
                    if fname == "first_value":
                        # global partition start lives on the first
                        # non-empty segment
                        lk = make_lookup(vals, va)
                        val, vv = lk(jnp.zeros((cap,), jnp.int64))
                    else:
                        # last PEER is local — peers are whole per segment
                        val, vv = vals[peer_end], va[peer_end]
                    out_c[ci.id] = val
                    out_v[ci.id] = vv & sel_sorted
                    continue
                # running aggregates to the last peer (default RANGE
                # UNBOUNDED PRECEDING..CURRENT ROW): local prefix value
                # plus the prior segments' whole-segment totals
                lv = sel_sorted if valid is None else (sel_sorted & valid)
                if fname in ("sum", "count", "avg"):
                    if fname == "count" and vals is None:
                        vals = jnp.ones((cap,), dtype=jnp.int64)
                    acc = (jnp.float64 if vals.dtype.kind == "f"
                           else jnp.int64)
                    contrib = jnp.where(lv, vals.astype(acc), acc(0))
                    cs = jnp.cumsum(contrib)
                    cnt = jnp.cumsum(lv.astype(jnp.int64))
                    ps = jnp.sum(jnp.where(
                        prior_mask, lax.all_gather(
                            jnp.sum(contrib), SEG_AXIS), acc(0)))
                    pc = jnp.sum(jnp.where(
                        prior_mask, lax.all_gather(
                            jnp.sum(lv.astype(jnp.int64)), SEG_AXIS), 0))
                    s = cs[peer_end] + ps
                    c = cnt[peer_end] + pc
                    if fname == "count":
                        out_c[ci.id] = c
                        out_v.pop(ci.id, None)
                    elif fname == "sum":
                        out_c[ci.id] = s
                        out_v[ci.id] = c > 0
                    else:
                        a = (s.astype(jnp.float64)
                             / jnp.where(c == 0, 1, c).astype(jnp.float64))
                        if scale:
                            a = a / (10.0 ** scale)
                        out_c[ci.id] = a
                        out_v[ci.id] = c > 0
                    continue
                # min / max (identity-fill rule of ops/window.py)
                if vals.dtype.kind == "f":
                    ident = jnp.array(jnp.inf if fname == "min"
                                      else -jnp.inf, vals.dtype)
                else:
                    info = jnp.iinfo(vals.dtype)
                    ident = jnp.array(info.max if fname == "min"
                                      else info.min, vals.dtype)
                filled = jnp.where(lv, vals, ident)
                op = jnp.minimum if fname == "min" else jnp.maximum
                run_ = (lax.cummin(filled) if fname == "min"
                        else lax.cummax(filled))
                g_t = lax.all_gather(
                    jnp.min(filled) if fname == "min"
                    else jnp.max(filled), SEG_AXIS)
                prior_red = (jnp.min(jnp.where(prior_mask, g_t, ident))
                             if fname == "min"
                             else jnp.max(jnp.where(prior_mask, g_t,
                                                    ident)))
                cnt = jnp.cumsum(lv.astype(jnp.int64))
                pc = jnp.sum(jnp.where(
                    prior_mask, lax.all_gather(
                        jnp.sum(lv.astype(jnp.int64)), SEG_AXIS), 0))
                out_c[ci.id] = op(run_[peer_end], prior_red)
                out_v[ci.id] = (cnt[peer_end] + pc) > 0
            return Batch(out_c, out_v, sel_sorted)

        return run

    # ---- union ---------------------------------------------------------
    def _c_union(self, plan: Union):
        fns = [self._compile_node(c) for c in plan.inputs]
        branch_ids = plan.branch_ids
        loci = [c.locus for c in plan.inputs]

        def run(ctx):
            from jax import lax

            parts_c = {uc.id: [] for uc in plan.cols}
            parts_v = {uc.id: [] for uc in plan.cols}
            parts_sel = []
            for fn, ids, locus in zip(fns, branch_ids, loci):
                b = fn(ctx)
                sel = b.selection()
                if locus is not None and locus.kind in (
                        LocusKind.SEGMENT_GENERAL, LocusKind.GENERAL):
                    # replicated branch: keep one segment's copy
                    sel = sel & (lax.axis_index(SEG_AXIS) == 0)
                parts_sel.append(sel)
                for uc, bid in zip(plan.cols, ids):
                    parts_c[uc.id].append(b.cols[bid])
                    v = b.valids.get(bid)
                    parts_v[uc.id].append(
                        v if v is not None else jnp.ones_like(sel))
            cols = {k: jnp.concatenate(v) for k, v in parts_c.items()}
            valids = {k: jnp.concatenate(v) for k, v in parts_v.items()}
            sel = jnp.concatenate(parts_sel)
            return Batch(cols, valids, sel)

        return run

    # ---- sort / limit --------------------------------------------------
    def _sort_keys(self, batch: Batch, keys):
        ev = Evaluator(batch, self.consts)
        out = []
        for e, desc, nf in keys:
            v, valid = ev.value(e)
            lut = None
            if e.type.kind is T.Kind.TEXT:
                d = getattr(e, "_dict_ref", None)
                if d is None and isinstance(e, E.ColRef):
                    d = self._dict_for_col(e.name)
                if d is not None:
                    dic = self.store.dictionary(*d)
                    order = np.argsort(np.argsort(dic.values, kind="stable"), kind="stable")
                    lut = jnp.asarray(
                        np.concatenate([order.astype(np.int32), [np.int32(-1)]]))
            out.append(sort_ops.SortKey(v, valid, e.type, desc, nf, rank_lut=lut))
        return out

    def _c_sort(self, plan: Sort):
        child_fn = self._compile_node(plan.child)
        keys = plan.keys
        cap = self._capacity_of(plan.child)
        key_bounds = getattr(plan, "key_bounds", None)
        fid_pack = None
        # mirror order_pack_bits' static feasibility: registering a flag
        # that runtime packing can never use ships a permanently-zero flag
        # (plus a pmax collective in multihost) per execution (advisor r3)
        if (key_bounds is not None
                and self._nid(plan) not in self.pack_disabled
                and _static_order_packable(keys, key_bounds)):
            fid_pack = f"pack_overflow_{len(self.flags)}"
            self.flags.append(fid_pack)
            self.flag_packs[fid_pack] = self._nid(plan)
        else:
            key_bounds = None

        def run(ctx):
            b = child_fn(ctx)
            sk = self._sort_keys(b, keys)
            kb = key_bounds
            if kb is not None and sort_ops.order_pack_bits(sk, kb) is None:
                kb = None
            perm, sel_sorted, viol = sort_ops.sort_batch(
                sk, b.selection(), cap, kb)
            if fid_pack is not None:
                ctx["flags"].append(
                    (fid_pack, viol if viol is not None
                     else jnp.zeros((), bool)))
            cols, valids = sort_ops.apply_perm(b.cols, b.valids, perm)
            return Batch(cols, valids, sel_sorted)

        return run

    def _c_limit(self, plan: Limit):
        child_fn = self._compile_node(plan.child)
        cap = self._capacity_of(plan.child)
        # LIMIT 0 is a real limit ('or' would treat 0 as no-limit and
        # disagree with _capacity_of's 'is not None' — advisor finding r1)
        k = min(cap, (cap if plan.limit is None else plan.limit) + plan.offset)
        compacted = isinstance(plan.child, Sort)
        # a buried Limit (not the host-trimmed one below the gather) must
        # drop its OFFSET prefix itself: rows are compacted live-first, so
        # masking the first `offset` positions removes exactly those rows
        device_offset = plan.offset if id(plan) != self._host_limit_node else 0

        def run(ctx):
            b = child_fn(ctx)
            if compacted:
                cols, valids, sel = sort_ops.limit(
                    b.cols, b.valids, b.selection(), k)
            else:
                # unsorted LIMIT: gather-compact live rows (order-preserving,
                # no lax.sort) straight into the k-slot output
                cols, valids, sel = sort_ops.compact(
                    b.cols, b.valids, b.selection(), k)
            if device_offset:
                sel = sel & (jnp.arange(k, dtype=jnp.int32) >= device_offset)
            return Batch(cols, valids, sel)

        return run


def _collect_dict_refs(plan: Plan, out: dict):
    for c in plan.out_cols():
        if c.dict_ref is not None:
            out[c.id] = c.dict_ref
    for ch in plan.children:
        _collect_dict_refs(ch, out)


def _collect_value_dict_refs(v, out: dict):
    """Dictionary refs reachable from an arbitrary plan-node field value:
    expression trees carry them as ``_dict_ref`` attributes (hash LUTs,
    sort-rank LUTs bake that dictionary's CONTENT into the program),
    ColInfos as their ``dict_ref`` field. Feeds shape_signature."""
    if isinstance(v, E.Expr):
        for n in E.walk(v):
            d = getattr(n, "_dict_ref", None)
            if d is not None:
                out[("expr", id(n))] = tuple(d)
    elif isinstance(v, (tuple, list)):
        for x in v:
            _collect_value_dict_refs(x, out)
    elif getattr(v, "dict_ref", None) is not None:
        out[("ci", id(v))] = tuple(v.dict_ref)
