"""Pipelined host staging: the bufmgr/smgr read-ahead layer of the scan.

The reference keeps scans fed by overlapping disk I/O, decode, and tuple
delivery (heap/aocs_beginscan over the buffer manager); our reproduction
staged every cold scan through one serial Python loop — read, CRC+zlib
decode, pad, concatenate, transfer, per segment and per column. This
module supplies the three pipeline pieces the executor composes:

  - a shared READ POOL (``pool(settings)``): every (table, segment) unit
    of a statement's input spec reads+decodes concurrently. The native
    codec, zlib, and file I/O all release the GIL, so the pool gets real
    parallelism; TableStore's caches and read-path self-heal are
    thread-safe under it. ``scan_threads`` sizes it (0 = auto).
  - IN-PLACE staging buffers (``assemble``): one preallocated
    ``[nseg * cap]`` host array per staged column that per-segment decoded
    arrays are written into directly — replacing the pad-then-concatenate
    pair of copies (and skipping even that one copy when a single
    segment's array already fills the buffer exactly).
  - a spill-pass PREFETCHER (``PassPrefetcher``): while pass k's jitted
    program runs, a background thread warms pass k+1's cold block reads
    into the block cache (JAX async dispatch leaves the host idle there).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from greengage_tpu.storage.blockcache import MISS  # noqa: F401 — one
# 'absent' sentinel shared with the store's caches (re-exported for the
# executor), so a lookup can never compare against the wrong module's


def scan_thread_count(settings) -> int:
    n = int(getattr(settings, "scan_threads", 0) or 0)
    if n <= 0:
        n = min(8, os.cpu_count() or 1)
    return max(n, 1)


class _InlineFuture:
    __slots__ = ("_value", "_err")

    def __init__(self, fn, args):
        try:
            self._value, self._err = fn(*args), None
        except BaseException as e:   # re-raised at result(), like a Future
            self._value, self._err = None, e

    def result(self):
        if self._err is not None:
            raise self._err
        return self._value


class _InlinePool:
    """scan_threads = 1: run units eagerly on the calling thread (no pool
    handoff overhead, deterministic single-threaded debugging)."""

    def submit(self, fn, *args):
        return _InlineFuture(fn, args)


_pool: ThreadPoolExecutor | None = None
_pool_size = 0
_pool_mu = threading.Lock()
_inline = _InlinePool()


def pool(settings):
    """The process-wide staging pool, resized when scan_threads changes.
    The displaced pool is NOT shut down here — a concurrent statement may
    still be submitting to it; dropping the reference lets it drain its
    in-flight units and be reclaimed once every holder finishes
    (ThreadPoolExecutor workers exit when their executor is collected)."""
    n = scan_thread_count(settings)
    if n <= 1:
        return _inline
    global _pool, _pool_size
    with _pool_mu:
        if _pool is None or _pool_size != n:
            _pool = ThreadPoolExecutor(max_workers=n,
                                       thread_name_prefix="gg-stage")
            _pool_size = n
        return _pool


def pool_queue_depth() -> int:
    """Read units waiting for a staging-pool thread right now — the
    `gg metrics` staging_pool_queue_depth gauge (a persistent backlog
    here means scan_threads is undersized for the workload)."""
    with _pool_mu:   # ps/metrics-frame rate; never on the read path
        p = _pool
    if p is None:
        return 0
    try:
        return p._work_queue.qsize()
    except (AttributeError, NotImplementedError):
        return 0


def fill_buffer(nseg: int, cap: int, dtype, parts, fill=0) -> np.ndarray:
    """One staging buffer for one column: ``parts`` yields (seg, array)
    with len(array) <= cap; every other position holds ``fill``. When a
    single segment's array already IS the full buffer (nseg == 1,
    len == cap, right dtype) it stages as-is — the no-copy fast path the
    old pad-then-concatenate could never take."""
    parts = list(parts)
    if nseg == 1 and len(parts) == 1:
        arr = parts[0][1]
        if len(arr) == cap and arr.dtype == dtype:
            return np.ascontiguousarray(arr)
    # np.empty + explicit padding of only the UNFILLED tails: the data
    # slices are about to be overwritten anyway, so a full-buffer memset
    # (np.full) would touch every byte twice
    out = np.empty(nseg * cap, dtype=dtype)
    filled = {}
    for seg, arr in parts:
        n = len(arr)
        if n:
            out[seg * cap: seg * cap + n] = arr
        filled[seg] = max(filled.get(seg, 0), n)
    for seg in range(nseg):
        n = filled.get(seg, 0)
        if n < cap:
            out[seg * cap + n: (seg + 1) * cap] = fill
    return out


class PassPrefetcher:
    """Warm the next spill pass's block reads while the current pass's
    device program runs. All passes share the same committed files (row
    ranges slice AFTER the read), so warming is a cheap cache probe when
    the budget held and a real read-ahead when eviction emptied it.
    Prefetch must never fail or outlive the query: errors are swallowed,
    close() joins."""

    def __init__(self, executor, input_spec, snapshot):
        from greengage_tpu.runtime import interrupt

        self.executor = executor
        self.snapshot = snapshot
        # the spawning statement's interrupt context: _warm polls it
        # between units so a cancelled statement's prefetcher dies at the
        # next unit boundary instead of reading the whole next pass (and
        # close() below never outwaits a cancelled warm loop)
        self._ctx = interrupt.REGISTRY.current()
        # (table, plain storage columns) units; aux/virtual tables skipped
        self.units = []
        for table, cols, _cap, _direct, _prune, child_parts, _dyn \
                in input_spec:
            if table.startswith("@"):
                continue
            plain = [c for c in cols if not c.startswith("@")]
            for t in (child_parts if child_parts is not None else (table,)):
                self.units.append((t, plain))
        self.enabled = bool(getattr(executor.settings, "spill_prefetch",
                                    True)) and bool(self.units)
        self._thread: threading.Thread | None = None

    def _warm(self) -> None:
        try:
            store = self.executor.store
            reg = store.blockcache
            for table, cols in self.units:
                for seg in self.executor._local_segments():
                    if self._ctx is not None and self._ctx.cancelled:
                        return   # statement is dying: stop warming for it
                    # budget guard: a table bigger than the cache would
                    # only evict its own (and the running pass's) blocks —
                    # stop warming once the registry nears its limit
                    # instead of thrashing it
                    if reg.total_bytes >= 0.9 * reg.limit_bytes():
                        return
                    store.read_segment(table, seg, cols, self.snapshot)
        except Exception:
            pass   # a failed prefetch is only a lost warm-up

    def kick(self) -> None:
        if not self.enabled or (self._thread is not None
                                and self._thread.is_alive()):
            return
        self._thread = threading.Thread(target=self._warm, daemon=True,
                                        name="gg-spill-prefetch")
        self._thread.start()

    def close(self) -> None:
        """Join the warm thread, bounded. Runs on the statement thread —
        poll the statement's cancellation so a dying statement stops
        waiting after the warm loop's current unit instead of sitting
        out the full drain (lint_interrupts thread-join coverage)."""
        t = self._thread
        if t is None:
            return
        deadline = time.monotonic() + 60.0
        while t.is_alive() and time.monotonic() < deadline:
            if self._ctx is not None and self._ctx.cancelled:
                # _warm observes the same flag at its next unit boundary
                # and exits; one bounded join covers that last unit
                t.join(timeout=5.0)
                break
            t.join(timeout=0.25)
        self._thread = None
