"""Tiered spill workfile: host RAM -> compressed disk segments.

The reference's workfile manager (workfile_mgr.c) spills hash batches to
disk files; our spill (exec/spill.py) kept every captured pass as live
numpy arrays — host RAM was the only workfile tier, so a statement whose
captured passes exceeded host memory died on host OOM instead of
degrading to disk bandwidth. This module makes the workfile explicit and
tiered:

  - every captured pass lands in the HOST-RAM tier, byte-accounted to
    the statement's 'spill' owner (runtime/memaccount.py) and the
    ``spill_tier_ram_bytes`` gauge;
  - once a statement's retained passes exceed ``spill_host_limit_mb``
    the COLDEST passes (earliest captured = last merged) demote to one
    compressed segment file each under ``spill_dir`` via the native
    codec (storage/native.py frames, CRC-checked on read), moving their
    bytes to the ``spill_tier_disk_bytes`` gauge;
  - ``assemble()`` merges every pass into ONE preallocated buffer per
    column (single peak — the old append-then-concatenate transiently
    held 2x the workfile), promoting disk passes back to RAM on the
    motion pipeline (exec/motionpipe.py) so pass k+1's read+decode
    overlaps pass k's buffer fill: merge time tends to
    max(decode I/O, fill bandwidth) rather than their sum.

Files are named ``gg-spill-<pid>-<seq>-<token>.wf`` and deleted as each
pass promotes (and unconditionally at ``close()``); ``sweep_orphans``
removes files whose owning process is dead — Database init calls it on
the coordinator so a kill mid-pass never leaks spill segments.

Tier decisions are HOST-LOCAL and invisible to the pass/bucket schedule
(which stays a pure function of compiled estimates + settings), so a
multihost gang's lockstep schedules are unaffected by how much host RAM
each process happens to have.
"""

from __future__ import annotations

import errno
import itertools
import os
import re
import threading

import numpy as np

from greengage_tpu.runtime import memaccount
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters

_FILE_RE = re.compile(r"^gg-spill-(\d+)-\d+-[0-9a-f]+\.wf$")
_seq = itertools.count(1)

# process-wide tier totals behind the spill_tier_{ram,disk}_bytes gauges
# (multiple concurrent spilling statements share them)
_tier_mu = threading.Lock()
_tier_ram = 0
_tier_disk = 0


def _tier_add(ram: int = 0, disk: int = 0) -> None:
    global _tier_ram, _tier_disk
    with _tier_mu:
        _tier_ram = max(_tier_ram + int(ram), 0)
        _tier_disk = max(_tier_disk + int(disk), 0)
        counters.set("spill_tier_ram_bytes", _tier_ram)
        counters.set("spill_tier_disk_bytes", _tier_disk)


def spill_dir_of(settings, store) -> str:
    d = str(getattr(settings, "spill_dir", "") or "")
    return d if d else os.path.join(store.root, "spill")


def sweep_orphans(directory: str) -> int:
    """Remove spill segment files owned by DEAD processes (a kill mid-pass
    leaves them behind; close() handles every live-process path). Returns
    the number removed; never raises — recovery must not fail on a
    half-written orphan."""
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        m = _FILE_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)          # signal 0: existence probe only
            continue                 # owner is alive — not an orphan
        except OSError as e:
            if e.errno != errno.ESRCH:
                continue             # EPERM: alive under another uid
        try:
            os.unlink(os.path.join(directory, name))
            removed += 1
        except OSError:
            pass
    if removed:
        counters.inc("spill_orphan_sweep_total", removed)
    return removed


class _Pass:
    __slots__ = ("rows", "data", "path", "index", "ram_bytes", "disk_bytes")
    # data: {col id: (array, valids | None)} while RAM-resident; None when
    # demoted. index: [(col id, dtype str, has_valids)] in frame order.


class SpillWorkfile:
    """One statement's captured passes for one spill phase. Single-owner:
    the statement thread adds/assembles/closes; only assemble()'s
    promotion stage callable runs off-thread (on disjoint passes)."""

    def __init__(self, executor, cols_spec, item: str):
        self.cols_spec = list(cols_spec)
        self.item = item
        self.settings = executor.settings
        self.dir = spill_dir_of(executor.settings, executor.store)
        limit_mb = int(getattr(executor.settings, "spill_host_limit_mb",
                               512) or 0)
        # 0 = RAM-only (the pre-tiered behavior: never touch disk)
        self.limit_bytes = limit_mb << 20 if limit_mb > 0 else None
        self.compresslevel = int(getattr(executor.settings,
                                         "default_compresslevel", 1))
        self._passes: list[_Pass] = []
        self._ram_bytes = 0
        self.stats: list = []        # per-pass Result.stats (EXPLAIN ANALYZE)
        # result-metadata donor fields from the FIRST captured pass
        self.columns = None
        self.order = None
        self.base_stats = None
        self._any_invalid = {c.id: False for c in self.cols_spec}
        self._closed = False

    # ---- capture -------------------------------------------------------
    def add(self, res) -> None:
        """Capture one pass Result: columns move to host arrays in the RAM
        tier (the device handles drop with the Result), metadata and stats
        are retained, and the coldest passes demote once the statement's
        RAM tier exceeds its budget."""
        faults.check("spill_capture")
        p = _Pass()
        p.data = {}
        p.path = None
        p.index = []
        p.disk_bytes = 0
        nb = 0
        rows = 0
        for c in self.cols_spec:
            a = np.asarray(res.cols[c.id])
            rows = len(a)
            v = res.valids.get(c.id)
            if v is not None:
                v = np.asarray(v, bool)
                self._any_invalid[c.id] = True
                nb += int(v.nbytes)
            nb += int(a.nbytes)
            p.data[c.id] = (a, v)
            p.index.append((c.id, a.dtype.str, v is not None))
        p.rows = rows
        p.ram_bytes = nb
        self._passes.append(p)
        self.stats.append(res.stats)
        if self.columns is None:
            self.columns = res.columns
            self.order = list(getattr(res, "_order", []) or [])
            self.base_stats = dict(res.stats or {})
        self._ram_bytes += nb
        memaccount.charge("spill", nb, item=self.item)
        _tier_add(ram=nb)
        if self.limit_bytes is not None:
            self._demote_over(self.limit_bytes)

    def _demote_over(self, limit: int) -> None:
        """Demote coldest-first (earliest captured) until the RAM tier
        fits; the pass being captured right now stays resident."""
        for p in self._passes[:-1]:
            if self._ram_bytes <= limit:
                return
            if p.data is not None:
                self._demote(p)
        # all older passes are on disk: demote the newest too if the tier
        # still overflows (one pass bigger than the whole budget)
        if self._ram_bytes > limit and self._passes \
                and self._passes[-1].data is not None:
            self._demote(self._passes[-1])

    def _demote(self, p: _Pass) -> None:
        from greengage_tpu.storage import native

        os.makedirs(self.dir, exist_ok=True)
        name = (f"gg-spill-{os.getpid()}-{next(_seq)}-"
                f"{os.urandom(4).hex()}.wf")
        path = os.path.join(self.dir, name)
        nbytes = 0
        with open(path, "wb") as f:
            for cid, _dt, has_v in p.index:
                a, v = p.data[cid]
                frame = native.block_encode(
                    np.ascontiguousarray(a), len(a),
                    level=self.compresslevel)
                f.write(frame)
                nbytes += len(frame)
                if has_v:
                    frame = native.block_encode(
                        np.ascontiguousarray(v).view(np.uint8), len(v),
                        level=self.compresslevel)
                    f.write(frame)
                    nbytes += len(frame)
            f.flush()
        p.path = path
        p.disk_bytes = nbytes
        p.data = None
        self._ram_bytes -= p.ram_bytes
        memaccount.charge("spill", -p.ram_bytes, item=self.item)
        _tier_add(ram=-p.ram_bytes, disk=nbytes)
        p.ram_bytes = 0
        counters.inc("spill_demote_total")

    def _promote(self, p: _Pass) -> dict:
        """Read one demoted pass back: -> {col id: (array, valids|None)}.
        CRC verification rides the codec (CorruptionError on a torn
        frame)."""
        from greengage_tpu.storage import native

        with open(p.path, "rb") as f:
            buf = f.read()
        out = {}
        off = 0
        for cid, dt, has_v in p.index:
            raw, _n, used = native.block_decode(buf[off:])
            off += used
            a = np.frombuffer(raw, dtype=np.dtype(dt))
            v = None
            if has_v:
                raw, _n, used = native.block_decode(buf[off:])
                off += used
                v = np.frombuffer(raw, dtype=np.uint8).astype(bool)
            out[cid] = (a, v)
        counters.inc("spill_promote_total")
        return out

    # ---- merge ---------------------------------------------------------
    def assemble(self):
        """Merge every pass into one preallocated buffer per column ->
        (cols, valids), valids[c] None when every pass was all-valid.
        Single-peak: each pass's tier bytes release as its rows land in
        the merged buffer. Disk passes promote on the motion pipeline so
        pass k+1's read+decode overlaps pass k's fill."""
        from greengage_tpu.exec import motionpipe

        total = sum(p.rows for p in self._passes)
        dtypes = {}
        for p in self._passes:
            for cid, dt, _hv in p.index:
                d = np.dtype(dt)
                dtypes[cid] = (d if cid not in dtypes
                               else np.result_type(dtypes[cid], d))
        cols = {c.id: np.empty(total, dtype=dtypes.get(c.id, np.int64))
                for c in self.cols_spec}
        valids = {c.id: (np.ones(total, dtype=bool)
                         if self._any_invalid[c.id] else None)
                  for c in self.cols_spec}
        offsets = []
        off = 0
        for p in self._passes:
            offsets.append(off)
            off += p.rows

        def stage(p, _i):
            return p.data if p.data is not None else self._promote(p)

        def fill(data, p, i):
            lo = offsets[i]
            hi = lo + p.rows
            for cid in cols:
                a, v = data[cid]
                cols[cid][lo:hi] = a
                if valids[cid] is not None and v is not None:
                    valids[cid][lo:hi] = v
            self._release(p)
            return None

        motionpipe.run_pipeline(self._passes, stage, fill,
                                settings=self.settings, label="workfile")
        self._passes = []
        nb = sum(int(a.nbytes) for a in cols.values())
        nb += sum(int(v.nbytes) for v in valids.values() if v is not None)
        memaccount.charge("spill", nb, item=self.item)
        return cols, valids

    def _release(self, p: _Pass) -> None:
        if p.data is not None:
            p.data = None
            self._ram_bytes -= p.ram_bytes
            memaccount.charge("spill", -p.ram_bytes, item=self.item)
            _tier_add(ram=-p.ram_bytes)
            p.ram_bytes = 0
        if p.path is not None:
            try:
                os.unlink(p.path)
            except OSError:
                pass
            _tier_add(disk=-p.disk_bytes)
            p.path = None
            p.disk_bytes = 0

    def close(self) -> None:
        """Release every retained pass (idempotent): uncharge RAM-tier
        bytes, delete disk segments. Runs in the spill paths' finally so
        an error (or cancellation) mid-schedule leaks nothing."""
        if self._closed:
            return
        self._closed = True
        for p in self._passes:
            self._release(p)
        self._passes = []
