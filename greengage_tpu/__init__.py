"""greengage_tpu — a TPU-native MPP analytical query engine.

A brand-new framework with the capabilities of GreengageDB (Greenplum-lineage
PostgreSQL MPP data warehouse), redesigned TPU-first:

- segments -> chips of a ``jax.sharding.Mesh`` (axis "seg")
- slice/Motion execution -> whole-plan compilation under ``shard_map`` where
  Redistribute Motion = ``lax.all_to_all``, Broadcast Motion = ``all_gather``,
  Gather Motion = device->host gather (reference: src/backend/cdb/motion/)
- volcano tuple-at-a-time -> vectorized columnar batch operators with
  validity + selection masks (reference: src/backend/executor/)
- AOCS column store -> per-column compressed block files with checksums and
  manifest-based MVCC commit (reference: src/backend/access/aocs/aocsam.c)
- locus-based motion planning (reference: src/backend/cdb/cdbpathlocus.c,
  cdbpath.c:922 cdbpath_motion_for_join)

See SURVEY.md for the full structural map of the reference.
"""

import os

import jax

# Decimals are stored/computed as scaled int64 for SQL exactness (the
# reference relies on PostgreSQL numeric); int64 on TPU is emulated with
# int32 pairs which is acceptable for the bandwidth-bound analytical ops.
jax.config.update("jax_enable_x64", True)

# Some TPU environments force their platform at interpreter start
# (sitecustomize), overriding JAX_PLATFORMS. GGTPU_PLATFORM wins if set —
# e.g. GGTPU_PLATFORM=cpu with
# XLA_FLAGS=--xla_force_host_platform_device_count=8 gives the virtual
# demo cluster regardless of plugin defaults.
if os.environ.get("GGTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["GGTPU_PLATFORM"])

__version__ = "0.1.0"

from greengage_tpu.api import Database, connect  # noqa: E402,F401
