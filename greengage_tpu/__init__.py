"""greengage_tpu — a TPU-native MPP analytical query engine.

A brand-new framework with the capabilities of GreengageDB (Greenplum-lineage
PostgreSQL MPP data warehouse), redesigned TPU-first:

- segments -> chips of a ``jax.sharding.Mesh`` (axis "seg")
- slice/Motion execution -> whole-plan compilation under ``shard_map`` where
  Redistribute Motion = ``lax.all_to_all``, Broadcast Motion = ``all_gather``,
  Gather Motion = device->host gather (reference: src/backend/cdb/motion/)
- volcano tuple-at-a-time -> vectorized columnar batch operators with
  validity + selection masks (reference: src/backend/executor/)
- AOCS column store -> per-column compressed block files with checksums and
  manifest-based MVCC commit (reference: src/backend/access/aocs/aocsam.c)
- locus-based motion planning (reference: src/backend/cdb/cdbpathlocus.c,
  cdbpath.c:922 cdbpath_motion_for_join)

See SURVEY.md for the full structural map of the reference.
"""

import os

import jax

# Decimals are stored/computed as scaled int64 for SQL exactness (the
# reference relies on PostgreSQL numeric); int64 on TPU is emulated with
# int32 pairs which is acceptable for the bandwidth-bound analytical ops.
jax.config.update("jax_enable_x64", True)

# Some TPU environments force their platform at interpreter start
# (sitecustomize), overriding JAX_PLATFORMS. GGTPU_PLATFORM wins if set —
# e.g. GGTPU_PLATFORM=cpu with
# XLA_FLAGS=--xla_force_host_platform_device_count=8 gives the virtual
# demo cluster regardless of plugin defaults.
if os.environ.get("GGTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["GGTPU_PLATFORM"])

# Persistent XLA compilation cache: query programs are compiled per
# (plan shape, capacity tier); on TPU a single lax.sort costs ~25s to
# compile, so re-sessions (CLI invocations, bench reruns, server restarts)
# must reuse executables from disk — the "gang reuse across sessions"
# analog. GGTPU_XLA_CACHE=0 disables.
_cache = os.environ.get(
    "GGTPU_XLA_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "ggtpu_xla",
                 # separate dirs per platform: the tunneled TPU service
                 # compiles with different target features than local CPU,
                 # and mixed AOT entries trip feature-mismatch loads
                 os.environ.get("GGTPU_PLATFORM")
                 or os.environ.get("JAX_PLATFORMS") or "default"))
if _cache and _cache != "0":
    try:
        jax.config.update("jax_compilation_cache_dir", _cache)
        # cache even small programs: the tier-1 suite and the degraded-mode
        # subprocesses recompile the same statement shapes across dozens of
        # fresh processes, and on CPU those sub-2s compiles dominate the
        # suite's wall clock
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

__version__ = "0.1.0"

from greengage_tpu.api import Database, connect  # noqa: E402,F401
