"""Extension mechanism — scalar UDF registry + CREATE EXTENSION.

Reference parity: pg_proc function lookup + CREATE EXTENSION
(reference: src/backend/commands/extension.c:1546 CreateExtension,
src/backend/parser/parse_func.c func_get_detail; gpcontrib/ for the
shipped extension set). The TPU-native translation: a UDF is a
jax-traceable callable registered under (name, arity). The binder types
calls against the declared signature and the expression compiler INLINES
the callable into the fused XLA program — there is no fmgr call boundary,
so a UDF costs the same as a builtin (XLA fuses it into the surrounding
kernel). Extensions are plain Python modules that call register_scalar at
import; CREATE EXTENSION imports them and records the name in the catalog
so reopened clusters reload them.

All functions are STRICT (NULL in -> NULL out), matching the common PG
default; the evaluator AND-combines argument validity.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
from dataclasses import dataclass
from typing import Callable
import sys

from greengage_tpu import types as T


@dataclass(frozen=True)
class ScalarFunction:
    name: str
    fn: Callable                  # jax-traceable; receives device arrays
    arg_types: tuple[str, ...]    # 'float64'|'int64'|'numeric'|'bool'|'date'|'any'
    result_type: object           # T.SqlType, or 'first' (= first arg's type)
    extension: str                # '' for builtins
    masked: bool = False          # fn returns (value, invalid_bool_mask)


_REGISTRY: dict[tuple[str, int], ScalarFunction] = {}
_LOADED: list[str] = []
_LOADING: list[str] = []   # extension name currently importing (load())


def register_scalar(name: str, fn: Callable, arg_types, result_type,
                    extension: str | None = None, masked: bool = False) -> None:
    """Register a scalar UDF; overloading is by arity only (keyed on
    (lowercase name, nargs)). Re-registration replaces (idempotent module
    reloads). Functions registered during load() are tagged with that
    extension's name so visibility follows each database's catalog."""
    if extension is None:
        extension = _LOADING[-1] if _LOADING else ""
    spec = ScalarFunction(name.lower(), fn, tuple(arg_types), result_type,
                          extension, masked)
    key = (spec.name, len(spec.arg_types))
    old = _REGISTRY.get(key)
    if old is not None and old.extension != spec.extension:
        # an extension must not shadow a builtin (or another extension's
        # function) process-wide — that would change behavior in databases
        # that never created it
        owner = f'extension "{old.extension}"' if old.extension else "builtin"
        raise ValueError(
            f'function "{spec.name}"/{key[1]} conflicts with {owner}')
    _REGISTRY[key] = spec


def lookup(name: str, arity: int) -> ScalarFunction | None:
    return _REGISTRY.get((name.lower(), arity))


def arities(name: str) -> list[int]:
    return sorted(a for (n, a) in _REGISTRY if n == name.lower())


def loaded() -> list[str]:
    return list(_LOADED)


def load(name: str, cluster_path: str | None = None) -> None:
    """CREATE EXTENSION body: import the module (which registers its
    functions as an import side effect). Search order: the bundled
    contrib namespace, the cluster's installed packages
    (<cluster>/extensions/<name>, populated by ``gg pkg install`` — the
    gppkg analog), then any importable module of that name. A module
    that imports but registers NOTHING is rejected — `create extension
    json` must not silently record an arbitrary stdlib module."""

    pkg_root = (os.path.join(cluster_path, "extensions")
                if cluster_path else None)
    has_pkg = pkg_root and os.path.isdir(os.path.join(pkg_root, name))
    if name in _LOADED:
        # registration is process-global; per-database VISIBILITY is
        # enforced at bind time (catalog.extensions check). Guard the one
        # hazard: a same-named package in a DIFFERENT cluster's extensions
        # dir would silently reuse the first cluster's code
        if has_pkg:
            mod = sys.modules.get(name)
            modfile = getattr(mod, "__file__", "") or ""
            if mod is not None and not modfile.startswith(
                    os.path.abspath(pkg_root) + os.sep):
                raise ValueError(
                    f'extension "{name}" already loaded from '
                    f"{modfile or 'another source'} in this process; "
                    "same-named packages from two clusters cannot coexist")
        return
    if has_pkg and pkg_root not in sys.path:
        sys.path.insert(0, pkg_root)
    target = None
    for modname in (f"greengage_tpu.contrib.{name}", name):
        if importlib.util.find_spec(modname) is not None:
            target = modname
            break
    if target is None:
        raise ValueError(f'extension "{name}" is not available: no module '
                         f'"greengage_tpu.contrib.{name}" or "{name}"')
    before = len(_REGISTRY)
    _LOADING.append(name)
    try:
        # a failure INSIDE the module (missing dependency) propagates
        # as-is rather than being masked by a fallback import
        importlib.import_module(target)
    finally:
        _LOADING.pop()
    if len(_REGISTRY) == before and not any(
            sp.extension == name for sp in _REGISTRY.values()):
        raise ValueError(
            f'module "{target}" registered no functions; not a '
            f"greengage_tpu extension")
    _LOADED.append(name)


# --------------------------------------------------------------------------
# builtin math functions (the numeric slice of pg_proc the reference's
# planner assumes; src/include/catalog/pg_proc.h)
# --------------------------------------------------------------------------

def _register_builtins():
    import jax.numpy as jnp

    F, f64 = T.FLOAT64, ("float64",)
    for nm, fn in (("sqrt", jnp.sqrt), ("exp", jnp.exp), ("ln", jnp.log),
                   ("log", lambda x: jnp.log10(x)),
                   ("degrees", jnp.degrees), ("radians", jnp.radians),
                   ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
                   ("atan", jnp.arctan)):
        register_scalar(nm, fn, f64, F)
    register_scalar("power", jnp.power, ("float64", "float64"), F)
    register_scalar("atan2", jnp.arctan2, ("float64", "float64"), F)
    # floor/ceil/round/trunc keep float64 (deviation: PG returns numeric
    # for numeric input; the session layer can cast back)
    register_scalar("floor", jnp.floor, f64, F)
    register_scalar("ceil", jnp.ceil, f64, F)
    register_scalar("ceiling", jnp.ceil, f64, F)
    register_scalar("round", jnp.round, f64, F)
    register_scalar("round", lambda x, n: jnp.round(x * 10.0 ** n) / 10.0 ** n,
                    ("float64", "int64"), F)
    register_scalar("trunc", jnp.trunc, f64, F)
    # integer / sign-preserving
    register_scalar("abs", jnp.abs, ("numeric",), "first")

    def _mod(a, b):
        # truncation semantics, sign of the dividend (PG numeric mod);
        # mod(x, 0) yields NULL via the mask (the kernel-level deviation
        # documented at expr_eval.zero_invalid — PG raises)
        bad = b == 0
        safe = jnp.where(bad, jnp.int64(1), b)
        return a - safe * jnp.trunc(a / safe).astype(a.dtype), bad

    register_scalar("mod", _mod, ("int64", "int64"), "first", masked=True)
    register_scalar("sign", lambda x: jnp.sign(x).astype(jnp.int32),
                    ("numeric",), T.INT32)
    # GREATEST/LEAST/COALESCE/NULLIF live in ops/scalar.py, not here: PG's
    # ignore/inspect NULL arguments (they are expression constructs, not
    # strict functions) and the strict registry would silently return NULL.
    # round/trunc/mod keep their float64 forms here; the binder routes
    # DECIMAL arguments to the scale-exact ops/scalar.py variants first.


_register_builtins()
