"""Engine configuration — the GUC system analog (guc.c / guc_gp.c).

A small typed settings registry with per-session overrides; the Database
facade exposes SET/SHOW. Names loosely mirror the reference's GUCs
(gp_interconnect_queue_depth etc. -> motion capacity slack here).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Settings:
    # join probe-chain BOUND: the build/probe walks are dynamic-trip
    # while_loops that run only as deep as the worst real chain (2-4 at
    # load 1/3); the bound only caps pathological chains, flagging
    # overflow for the bigger-table retry tier
    hash_num_probes: int = 32
    hash_table_min: int = 256
    hash_table_max: int = 1 << 25
    # dense group-by path: used when the product of group-key domains
    # (dictionary sizes / bool) is at most this (scatter-free aggregation)
    dense_group_limit: int = 512
    # fused single-pass dense aggregation (ops/fused_agg.py pallas kernel):
    # one HBM pass for every aggregate of a small-domain GROUP BY; falls
    # back to the XLA per-aggregate path on unsupported shapes or kernel
    # compile failure (executor disables it for the retry)
    fused_dense_agg: bool = True
    fused_dense_min_rows: int = 1 << 16
    # the kernel unrolls domain x accumulators reductions per grid step and
    # keeps (accums, domain, 128)-lane scratch in VMEM: bound both so a
    # wide dense domain never triggers multi-minute Mosaic compiles or
    # VMEM exhaustion (the XLA path wins there anyway)
    fused_dense_max_domain: int = 64
    fused_dense_max_scratch_mb: int = 4
    # motion (gp_interconnect_queue_depth analog)
    motion_capacity_slack: float = 1.6  # per-destination bucket headroom
    motion_retry_tiers: int = 3         # capacity x4 per retry on overflow
    # pipelined motion (docs/PERF.md "Data movement"): motion_pipeline
    # overlaps the host side of bucketed spill schedules — bucket k+1's
    # staging runs on a background thread while bucket k computes; off =
    # the serial-phase loops (the microbench baseline).
    # motion_pipeline_buckets > 1 additionally splits each compiled
    # redistribute into that many sub-exchanges along the capacity axis
    # (row-order identical to the single all_to_all) so XLA can overlap
    # exchange k+1 with compute on exchange k's rows; 1 = the single
    # monolithic all_to_all (the pre-PR-18 program, byte-identical)
    motion_pipeline: bool = True
    motion_pipeline_buckets: int = 1
    # planner selection (the GUC 'optimizer' analog): on = Cascades-lite
    # memo search (planner/memo.py, the ORCA engine analog); off = the
    # left-deep Selinger DP / greedy order in the binder
    optimizer: bool = True
    explain_verbose: bool = False
    # memory protection (gp_vmem_protect_limit analog): estimated device
    # bytes a single query may allocate; 0 disables the check
    vmem_protect_limit_mb: int = 12288
    # mid-flight enforcement (vmem_tracker.c + redzone_handler.c +
    # runaway_cleaner.c analog): cluster-wide ceiling on the SUM of
    # in-flight statements' compiled estimates; crossing
    # runaway_red_zone x this flags the heaviest statement, which
    # terminates at its next cancellation point (retry-tier or spill-pass
    # boundary). 0 disables cross-statement enforcement.
    vmem_global_limit_mb: int = 0
    runaway_red_zone: float = 0.9
    # measured memory accounting (runtime/memaccount.py, the
    # vmem_tracker/memaccounting.c analog): attach XLA memory_analysis to
    # every cached executable, keep the per-statement owner tree, sample
    # device watermarks at span boundaries, and let admission + the
    # runaway cleaner prefer MEASURED executable bytes over the planner
    # estimate once the executable is warm (only when the backend reports
    # real temps — CPU reports none, so estimates keep governing there)
    mem_accounting_enabled: bool = True
    # feedback-driven cost calibration (planner/feedback.py): reconcile
    # per-node actual rows + measured executable bytes against planner
    # estimates after every execution, and apply the learned per-digest
    # row-scale corrections at plan time (bounded EWMA; a promotion
    # bumps the calibration version so the shape re-plans). Off =
    # estimates stay static (the store still reports via gg checkperf).
    cost_feedback: bool = True
    # hysteresis band around an applied correction: the EWMA candidate
    # must drift by more than this FACTOR before it re-applies (and
    # re-plans the shapes using it) — estimate noise inside the band
    # never invalidates cached plans
    cost_feedback_hysteresis: float = 1.5
    # on a device RESOURCE_EXHAUSTED the statement demotes to the spill
    # path once (the workfile fallback) before surfacing the typed
    # OutOfDeviceMemory; off = fail fast with the forensics dump only
    oom_spill_retry: bool = True
    # synchronous mirror replication after each committed write (the
    # synchronous_standby_names / syncrep gate analog); off = mirrors go
    # stale and are barred from promotion until `gg replicate`
    mirror_sync: bool = True
    # resource queue (resscheduler.c ResLockPortal analog): bound on
    # concurrent mesh statements (0 = unlimited), per-query estimated
    # device memory ceiling, and how long a statement may queue
    resource_queue_active: int = 0
    resource_queue_memory_mb: int = 0
    resource_queue_timeout_s: float = 30.0
    # resource groups: cluster-wide cap on concurrent mesh statements;
    # when it binds, the backoff scheduler picks the next group by
    # weighted consumed chip time (runtime/resgroup.py)
    resource_group_global_active: int = 0
    # storage
    default_compresstype: str = "zlib"
    default_compresslevel: int = 1
    # host data path (docs/PERF.md; the bufmgr/smgr pipeline analog):
    # scan_threads sizes the staging read+decode pool (0 = auto:
    # min(8, cpu count)); 1 disables concurrency entirely.
    scan_threads: int = 0
    # one byte budget for every block cache (decoded blocks, footers, raw
    # chunks, host predicates, deletion masks, staged device inputs) —
    # the shared_buffers analog, LRU-evicted across all of them
    scan_cache_limit_mb: int = 1024
    # spill passes warm the next pass's cold block reads on a background
    # thread while the current pass's jitted program runs
    spill_prefetch: bool = True
    # tiered spill workfile (exec/workfile.py; docs/PERF.md "Data
    # movement"): captured spill passes land in a byte-accounted host-RAM
    # tier; once a statement's retained passes exceed spill_host_limit_mb
    # the coldest passes demote to compressed segment files under
    # spill_dir (default <cluster>/spill when empty) and are promoted
    # back to RAM ahead of the merge schedule. 0 = RAM-only (the
    # pre-tiered behavior: the workfile never touches disk)
    spill_dir: str = ""
    spill_host_limit_mb: int = 512
    # window-partition spill (exec/spill.py spill_window_run): a window
    # whose working set exceeds the admission limit captures its input in
    # chunked passes, then runs the window over disjoint PARTITION BY
    # hash buckets — whole partitions per bucket, exact results. Off =
    # honest admission rejection (the pre-spill behavior)
    window_spill_enabled: bool = True
    # scalar data-path fusion (ops/scalar.py; docs/PERF.md "Scalar
    # data-path fusion"): lower raw-TEXT string-function chains to device
    # byte-window ops (E.RawStrOp) inside the fused programs; off = the
    # legacy per-row host chains (the microbench baseline). Dictionary-LUT
    # and date/numeric device scalars are always on — they have no host
    # fallback to compare against.
    scalar_device_enabled: bool = True
    # sampled-splitter range repartition for ordered global windows
    # (exec/compile.py _c_motion range branch): per-segment sample size
    # feeding the global splitter selection; larger = better balance for
    # skewed keys at a few KB of extra all_gather
    window_range_sample: int = 64
    # read-path self-heal (docs/ROBUSTNESS.md storage failure model): a
    # corrupt/missing block file is repaired from the IN-SYNC standby tree
    # and the read retried once; off = detect-and-quarantine only (the
    # file still quarantines, storage_ok fails, FTS failover takes over)
    storage_autorepair: bool = True
    # multihost control-plane deadlines + liveness (docs/ROBUSTNESS.md;
    # gp_segment_connect_timeout / gp_fts_probe_timeout family): silence
    # past these bounds classifies as WorkerDied instead of a hang
    mh_connect_deadline: float = 60.0   # gang assembly accept + (re)connect
    mh_ready_deadline: float = 120.0    # readiness acks (refresh+plan+verify)
    mh_ack_deadline: float = 600.0      # completion acks (compile+execute)
    mh_heartbeat_interval: float = 2.0  # idle ping/pong cadence; 0 disables
    # statement lifecycle (docs/ROBUSTNESS.md): statement_timeout arms a
    # deadline at statement start; the statement dies at its next
    # cancellation point (boundary-granular — a dispatched XLA program
    # runs to its boundary). 0 disables.
    statement_timeout_s: float = 0.0
    # read-only dispatch retry: after WorkerDied mid-dispatch, how long
    # the coordinator waits for the gang to re-form before serving the
    # statement on the degraded local path instead (writes never retry)
    mh_retry_window_s: float = 1.0
    # N-1 mesh re-formation (docs/ROBUSTNESS.md "Topology re-formation"):
    # on worker death the coordinator rebuilds the gang over the SURVIVORS
    # (mirror-promoted contents served from surviving roots) instead of
    # falling to the single-process degraded path; off = legacy degrade.
    # The deadline bounds how long re-formation waits for survivors to
    # redial the kept listener before adopting whoever arrived.
    mh_reform_enabled: bool = True
    mh_reform_deadline_s: float = 10.0
    # coordinator failover (docs/ROBUSTNESS.md "Coordinator failover"):
    # mh_coordinator_addrs is the ordered "host:port,host:port" list a
    # worker's CoordinatorLost redial walks — first the address it was
    # launched against, then the standby's listener — so a promoted
    # standby adopts the surviving gang without any process restart
    # (empty = redial the launch address only, the legacy behavior).
    # The standby watcher (`gg standby --watch`) pull-syncs the primary's
    # commit tail every standby_watch_interval_s and auto-promotes once
    # the primary's liveness beat has been silent past
    # standby_promote_deadline_s (the gp_fts_probe_timeout analog for the
    # coordinator itself; promotion fences the old primary first, so a
    # paused-not-dead coordinator cannot split-brain).
    mh_coordinator_addrs: str = ""
    standby_promote_deadline_s: float = 15.0
    standby_watch_interval_s: float = 1.0
    # per-table delta manifests (storage/manifest.py): fold the delta
    # backlog into the root snapshot once it reaches this many commits
    # (the checkpoint_segments analog); 0 folds on every commit
    manifest_delta_fold_threshold: int = 64
    # hot-table write scale (storage/manifest.py write-intent path,
    # runtime/ingest.py streaming plane): write_intents_enabled routes
    # autocommit appends through txid-named intent records (same-table
    # appenders commit with zero claim retries; off = the per-table CAS
    # for every write). Stream sessions buffer rows host-side up to
    # ingest_buffer_rows (overflow past an inline flush sheds, typed and
    # retryable), committing micro-batches at ingest_batch_rows rows or
    # ingest_batch_ms milliseconds — the durability watermarks. A stream
    # idle past ingest_stream_idle_s is flushed and closed by the
    # flusher (abandoned-client hygiene); 0 disables the deadline.
    write_intents_enabled: bool = True
    ingest_batch_rows: int = 4096
    ingest_batch_ms: float = 250.0
    ingest_buffer_rows: int = 65536
    ingest_stream_idle_s: float = 300.0
    # plan / executable cache (plancache.c prepared-statement analog;
    # docs/PERF.md "Plan cache"): plan_cache_params hoists plan-safe
    # literals into runtime parameters so one XLA executable serves every
    # value of a query shape (off = classic value-pinned plans);
    # plan_cache_size bounds BOTH the session's bound-plan LRU and the
    # executor's compiled-program LRU (each program entry pins an XLA
    # executable)
    plan_cache_params: bool = True
    plan_cache_size: int = 256
    # vectorized serving (exec/batchserve.py; docs/PERF.md "Vectorized
    # serving"): concurrent SELECTs sharing one literal-stripped statement
    # shape are collected during an admission window and executed as ONE
    # XLA dispatch over their stacked parameter vectors. Off by default —
    # a serving deployment opts in; the single-user path is unchanged.
    # batch_window_ms bounds how long a statement may wait for batch-mates
    # (the window only opens while the serving pipeline is busy — an idle
    # pipeline dispatches immediately, so the window costs latency only
    # when the device is the bottleneck anyway); batch_max_width flushes a
    # window early when it fills, and bounds the stacked width (widths
    # compile per pow2 bucket, so 1..max_width costs log2 compiles)
    batch_serving_enabled: bool = False
    batch_window_ms: float = 2.0
    batch_max_width: int = 16
    # ---- overload armor (docs/ROBUSTNESS.md "Overload protection") ----
    # bounded front end (runtime/server.py): cap on concurrent client
    # connections — excess connects get a typed too_many_connections
    # fast-fail (SQLSTATE 53300 analog) instead of silent thread growth;
    # 0 = unlimited (the embedded/test default behavior stays reachable)
    max_connections: int = 100
    # auth-handshake deadline for remote (TCP) peers: a connect that
    # never completes the challenge-response is closed, so a port-scan
    # or wedged client cannot pin a handler thread forever (0 = off)
    client_auth_deadline_s: float = 10.0
    # idle-read deadline between statements: a connection silent past
    # this is told idle_timeout and closed (0 = off, the default — BI
    # tools hold idle connections legitimately)
    client_idle_timeout_s: float = 0.0
    # maximum request-frame size (one newline-delimited JSON line): an
    # oversized frame is rejected with frame_too_large and the
    # connection closed (the stream cannot be resynced), so a multi-GB
    # line cannot OOM the host
    max_frame_bytes: int = 64 << 20
    # graceful-drain window for SqlServer.stop(): in-flight statements
    # are flagged shutdown and handler threads joined up to this bound
    # before their sockets are force-closed
    server_drain_s: float = 5.0
    # load shedding (runtime/resqueue.py shed_check, shared by the
    # resource queue and resource groups): cap on statements WAITING for
    # an admission slot — at the cap the statement is rejected with the
    # typed, retryable AdmissionShed (SQLSTATE 53300 analog) instead of
    # queueing forever; 0 = queue forever (legacy). Rejection ramps in
    # probabilistically from admission_shed_ramp x cap so the approach
    # to the cap sheds gradually, not as a cliff.
    admission_queue_limit: int = 0
    admission_shed_ramp: float = 0.75
    # serving-pipeline cap (exec/batchserve.py): members allowed to wait
    # across open admission windows; past it, new members shed to the
    # classic serial path (which the admission queue bounds) instead of
    # accumulating unboundedly while the device is busy. 0 = uncapped.
    batch_queue_limit: int = 512
    # memory-pressure brownout (runtime/overload.py): on sustained HBM
    # pressure (watermark fraction or an OOM streak) the engine enters a
    # typed brownout — block-cache budget x brownout_cache_factor, batch
    # serving disabled, admission ceiling x brownout_vmem_factor so new
    # statements prefer the spill tier — and exits only after every
    # signal stays clear for brownout_exit_s (hysteresis; the watermark
    # bar also drops to brownout_exit_pct while browned out)
    brownout_enabled: bool = True
    brownout_enter_pct: float = 0.92
    brownout_exit_pct: float = 0.80
    brownout_oom_events: int = 3
    brownout_window_s: float = 30.0
    brownout_exit_s: float = 5.0
    brownout_cache_factor: float = 0.5
    brownout_vmem_factor: float = 0.5
    # persistent XLA compilation cache directory, applied at Database init
    # (the warm-cache requirement in docs/PERF.md — a cold cache
    # recompiles every query shape once per process). Empty = leave the
    # process default; the GGTPU_XLA_CACHE env var overrides when set.
    xla_cache_dir: str = "~/.cache/ggtpu_xla"
    # jax's persistent cache never evicts (0.4.x), so init prunes the
    # active platform subdir oldest-first past this bound; 0 = unbounded
    xla_cache_limit_mb: int = 2048
    # plan-invariant validation (analysis/plancheck.py; the cdbmutate
    # checkPlan-before-dispatch analog): walk every planned statement and
    # raise a typed PlanInvariantError on Motion-placement / locality /
    # prune-shape violations BEFORE compile or dispatch. The walk is
    # O(plan nodes) of host attribute checks — noise next to planning —
    # so it defaults on everywhere, not just in tests
    plan_validate: bool = True
    # logging (log_statement / log_min_duration_statement analog): every
    # statement + errors land in <cluster>/log CSV files
    log_statement: bool = True
    # observability (docs/OBSERVABILITY.md; the gpperfmon analog):
    # trace_enabled records per-phase spans for every statement into the
    # bounded completed-trace ring (`gg trace <id>` exports Chrome
    # trace_event JSON); log_min_duration_ms additionally writes a
    # slow_statement log row (plan digest + trace id) and dumps the trace
    # JSON beside the CSV logs for any statement at/above the threshold
    # (-1 disables, 0 logs every statement)
    trace_enabled: bool = True
    trace_ring_size: int = 64
    log_min_duration_ms: float = -1.0
    # continuous archiving (archive_mode/archive_command analog): after
    # each committed write, ship the new manifest version + its new
    # segment files to archive_dir (storage/archive.py); `gg restore-pitr`
    # rebuilds any archived version
    archive_mode: bool = False
    archive_dir: str = ""

    _overrides: dict = field(default_factory=dict)

    def set(self, name: str, value) -> None:
        if not hasattr(self, name) or name.startswith("_"):
            raise ValueError(f'unrecognized configuration parameter "{name}"')
        cur = getattr(self, name)
        if isinstance(cur, bool):
            value = str(value).lower() in ("1", "true", "on", "yes")
        elif isinstance(cur, int):
            value = int(value)
        elif isinstance(cur, float):
            value = float(value)
        setattr(self, name, value)

    def show(self, name: str):
        if not hasattr(self, name) or name.startswith("_"):
            raise ValueError(f'unrecognized configuration parameter "{name}"')
        return getattr(self, name)
