"""Public API: connect() / Database — the libpq+psql analog surface.

Grows with the engine; the full query path lands in exec/session.py and is
re-exported here.
"""

from greengage_tpu.exec.session import Database  # noqa: F401


def connect(path: str | None = None, numsegments: int | None = None) -> "Database":
    """Open (or create) a database.

    path=None gives an in-memory single-host cluster; numsegments defaults to
    the number of visible JAX devices (each segment binds to one chip).
    """
    return Database(path=path, numsegments=numsegments)
