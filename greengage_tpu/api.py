"""Public API: connect() / Database — the libpq+psql analog surface.

Grows with the engine; the full query path lands in exec/session.py and is
re-exported here.
"""

from greengage_tpu.exec.session import Database  # noqa: F401


def connect(path: str | None = None, numsegments: int | None = None,
            mirrors: bool = False, multihost=None) -> "Database":
    """Open (or create) a database.

    path=None gives an in-memory single-host cluster; numsegments defaults to
    the number of visible JAX devices (each segment binds to one chip).
    mirrors=True creates a mirror per segment (replicated on every committed
    write; promoted by FTS on primary failure).
    multihost: a parallel.multihost.MultihostRuntime — the mesh then spans
    every process's devices (workers run `gg worker`).
    """
    return Database(path=path, numsegments=numsegments, mirrors=mirrors,
                    multihost=multihost)
