"""SQL type system for the TPU engine.

The reference inherits PostgreSQL's type system (pg_type catalog); we define a
small, TPU-friendly core with exact device representations:

- BOOL      -> bool_
- INT32     -> int32
- INT64     -> int64
- FLOAT64   -> float64 (host/CPU exactness; compute may downcast on TPU)
- DECIMAL   -> scaled int64 (scale = digits after the point). SQL-exact sums
               and products, no float drift (reference: PostgreSQL numeric).
- DATE      -> int32 days since 1970-01-01
- TEXT      -> int32 dictionary codes + host-side dictionary (per column).
               String predicates are evaluated on the host dictionary and
               become boolean lookup tables gathered on device, so arbitrary
               LIKE/regex cost O(dict) on host + one gather on device.

NULLs are carried out-of-band as validity masks (True = valid), mirroring the
columnar engines' approach rather than PostgreSQL's per-tuple null bitmap.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
import re
from decimal import Decimal, ROUND_HALF_UP

import numpy as np


class Kind(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT64 = "float64"
    DECIMAL = "decimal"
    DATE = "date"
    TEXT = "text"


@dataclass(frozen=True)
class SqlType:
    kind: Kind
    scale: int = 0  # decimal digits after the point (DECIMAL only)

    def __post_init__(self):
        if self.kind is not Kind.DECIMAL and self.scale != 0:
            raise ValueError("scale is only valid for DECIMAL")

    # ---- classification ------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.kind in (Kind.INT32, Kind.INT64, Kind.FLOAT64, Kind.DECIMAL)

    @property
    def is_integer(self) -> bool:
        return self.kind in (Kind.INT32, Kind.INT64)

    @property
    def is_orderable(self) -> bool:
        return True  # every core type (incl. BOOL, false < true) is orderable

    # ---- device representation ----------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(
            {
                Kind.BOOL: np.bool_,
                Kind.INT32: np.int32,
                Kind.INT64: np.int64,
                Kind.FLOAT64: np.float64,
                Kind.DECIMAL: np.int64,
                Kind.DATE: np.int32,
                Kind.TEXT: np.int32,  # dictionary codes
            }[self.kind]
        )

    def __str__(self) -> str:
        if self.kind is Kind.DECIMAL:
            return f"decimal(.,{self.scale})"
        return self.kind.value


BOOL = SqlType(Kind.BOOL)
INT32 = SqlType(Kind.INT32)
INT64 = SqlType(Kind.INT64)
FLOAT64 = SqlType(Kind.FLOAT64)
DATE = SqlType(Kind.DATE)
TEXT = SqlType(Kind.TEXT)


def decimal(scale: int) -> SqlType:
    return SqlType(Kind.DECIMAL, scale)


# --------------------------------------------------------------------------
# Promotion rules (mirrors PostgreSQL's implicit numeric promotion ladder)
# --------------------------------------------------------------------------

_NUM_RANK = {Kind.INT32: 0, Kind.INT64: 1, Kind.DECIMAL: 2, Kind.FLOAT64: 3}


def promote(a: SqlType, b: SqlType) -> SqlType:
    """Common type for comparison / arithmetic alignment of a and b."""
    if a == b:
        return a
    if a.kind == b.kind == Kind.DECIMAL:
        return decimal(max(a.scale, b.scale))
    if a.is_numeric and b.is_numeric:
        ra, rb = _NUM_RANK[a.kind], _NUM_RANK[b.kind]
        hi = a if ra >= rb else b
        lo = b if ra >= rb else a
        if hi.kind is Kind.DECIMAL:
            # integer joins decimal at the decimal's scale
            return decimal(hi.scale if lo.kind is not Kind.DECIMAL else max(a.scale, b.scale))
        return hi
    raise TypeError(f"cannot promote {a} and {b}")


def arith_result(op: str, a: SqlType, b: SqlType) -> SqlType:
    """Result type of a binary arithmetic op, PostgreSQL-flavored."""
    if op in ("+", "-") and a.kind is Kind.DATE and b.is_integer:
        return DATE
    if op == "-" and a.kind is Kind.DATE and b.kind is Kind.DATE:
        return INT32
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"{op} not defined for {a}, {b}")
    if a.kind is Kind.FLOAT64 or b.kind is Kind.FLOAT64:
        return FLOAT64
    if a.kind is Kind.DECIMAL or b.kind is Kind.DECIMAL:
        sa = a.scale if a.kind is Kind.DECIMAL else 0
        sb = b.scale if b.kind is Kind.DECIMAL else 0
        if op in ("+", "-"):
            return decimal(max(sa, sb))
        if op == "*":
            return decimal(sa + sb)
        if op == "/":
            # quotient computed in float64 then rescaled; keep 6 frac digits
            return decimal(max(sa, 6))
        raise TypeError(op)
    # PG semantics: integer / integer = integer (truncating)
    if a.kind is Kind.INT64 or b.kind is Kind.INT64:
        return INT64
    return INT32


def literal_type(v) -> SqlType:
    if isinstance(v, bool):
        return BOOL
    if isinstance(v, int):
        return INT32 if -(2**31) <= v < 2**31 else INT64
    if isinstance(v, float):
        return FLOAT64
    if isinstance(v, str):
        return TEXT
    raise TypeError(f"unsupported literal {v!r}")


# --------------------------------------------------------------------------
# Date helpers (host side)
# --------------------------------------------------------------------------

_EPOCH = np.datetime64("1970-01-01", "D")


def date_to_days(iso: str) -> int:
    return int((np.datetime64(iso, "D") - _EPOCH).astype(np.int64))


def days_to_date(days: int) -> str:
    return str(_EPOCH + np.timedelta64(int(days), "D"))


def from_string(v: str, t: SqlType):
    """Parse a string into a type's storage representation — the single
    coercion registry shared by COPY, INSERT literal binding, and loaders."""
    k = t.kind
    if k is Kind.TEXT:
        return v
    if k is Kind.DATE:
        return date_to_days(v)
    if k is Kind.DECIMAL:
        return decimal_to_int(v, t.scale)
    if k is Kind.FLOAT64:
        return float(v)
    if k is Kind.BOOL:
        s = v.strip().lower()
        if s in ("t", "true", "1", "yes", "on"):
            return True
        if s in ("f", "false", "0", "no", "off"):
            return False
        raise ValueError(f"invalid boolean {v!r}")
    return int(v)


def decimal_to_int(value, scale: int) -> int:
    """Parse a decimal literal (str/float/int) to scaled int64, half-up."""

    d = Decimal(str(value)).quantize(Decimal(1).scaleb(-scale), rounding=ROUND_HALF_UP)
    return int(d.scaleb(scale))


@dataclass
class Coded:
    """Bulk-load representation of a TEXT column: a small vocabulary plus an
    int32 code per row. Lets multi-million-row loads skip the per-string
    Python encode loop — the store maps vocab -> dictionary codes once and
    remaps the code array vectorized (the fast path the reference gets from
    gpfdist's parallel format parsing, gpfdist.c).
    """

    vocab: list
    codes: "np.ndarray"

    def __len__(self):
        return len(self.codes)

    def decode(self):
        import numpy as np

        return np.asarray(self.vocab, dtype=object)[self.codes]


def like_to_regex(pattern: str):
    """SQL LIKE pattern -> compiled regex (shared by the dictionary-LUT
    lowering and the raw-text host evaluator)."""

    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)
