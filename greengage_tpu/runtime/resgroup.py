"""Resource groups — per-group chip-time, concurrency and HBM shares.

Reference parity: resource groups (src/backend/utils/resgroup/resgroup.c)
give each role a slot-based concurrency cap, a memory share, and a CPU
share enforced through cgroups; the backoff sweeper
(src/backend/postmaster/backoff.c:723 BackoffSweeper) additionally skews
CPU between concurrent statements by priority. The TPU-native translation:
the scarce resources are CHIP TIME (one SPMD program occupies the mesh at
a time) and HBM, so a group carries

  concurrency      max concurrent mesh statements of this group (0 = off)
  memory_limit_mb  per-query estimated-bytes ceiling while running under
                   the group (feeds executor.effective_limit_bytes, so a
                   capped query SPILLS instead of failing)
  cpu_weight       backoff share: when a global slot frees, the waiter
                   from the group with the LEAST weighted consumed chip
                   time runs first (consumed_s / cpu_weight), standing in
                   for cgroup cpu.shares

Groups are session-wide objects persisted in the catalog; the ACTIVE
group is per thread (one server connection = one thread), set with
``SET resource_group = <name>``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime.logger import counters

DEFAULT_GROUP = "default_group"
ADMIN_GROUP = "admin_group"


class GroupTimeout(RuntimeError):
    pass


@dataclass
class ResourceGroup:
    name: str
    concurrency: int = 0          # 0 = unlimited (no slot gating)
    memory_limit_mb: int = 0      # 0 = inherit the global vmem ceiling
    cpu_weight: int = 100
    # runtime state (not persisted)
    active: int = 0
    waiting: int = 0
    admitted_total: int = 0
    timed_out_total: int = 0
    consumed_s: float = field(default=0.0)

    def to_dict(self) -> dict:
        return {"name": self.name, "concurrency": self.concurrency,
                "memory_limit_mb": self.memory_limit_mb,
                "cpu_weight": self.cpu_weight}

    @staticmethod
    def from_dict(d: dict) -> "ResourceGroup":
        return ResourceGroup(d["name"], d.get("concurrency", 0),
                             d.get("memory_limit_mb", 0),
                             d.get("cpu_weight", 100))


_local = threading.local()


def current_memory_limit_mb() -> int:
    """The calling thread's group memory ceiling (0 = none). Consulted by
    executor.effective_limit_bytes for every run."""
    return getattr(_local, "mem_limit_mb", 0)


class ResourceGroupManager:
    """Admission control over the group set + weighted-fair wakeup."""

    def __init__(self, settings, groups: dict[str, ResourceGroup] | None = None):
        self.settings = settings
        # RLock: add_listener fires the waker INLINE when the cancel flag
        # is already set, on the admitting thread, while it holds this
        # lock (same re-entrancy as ResourceQueue)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.groups: dict[str, ResourceGroup] = groups or {}
        for name, weight in ((DEFAULT_GROUP, 100), (ADMIN_GROUP, 300)):
            self.groups.setdefault(name, ResourceGroup(name, cpu_weight=weight))

    # ---- DDL ----------------------------------------------------------
    def create(self, name: str, **opts) -> None:
        with self._lock:
            if name in self.groups:
                raise ValueError(f'resource group "{name}" already exists')
            self.groups[name] = ResourceGroup(name, **opts)

    def drop(self, name: str) -> None:
        with self._lock:
            if name in (DEFAULT_GROUP, ADMIN_GROUP):
                raise ValueError(f'cannot drop built-in group "{name}"')
            g = self.groups.get(name)
            if g is None:
                raise ValueError(f'resource group "{name}" does not exist')
            if g.active or g.waiting:
                raise ValueError(
                    f'resource group "{name}" has active statements')
            del self.groups[name]
            if getattr(_local, "group", None) == name:
                _local.group = DEFAULT_GROUP

    def alter(self, name: str, **opts) -> None:
        with self._cond:
            g = self.groups.get(name)
            if g is None:
                raise ValueError(f'resource group "{name}" does not exist')
            for k, v in opts.items():
                setattr(g, k, v)
            self._cond.notify_all()   # a raised cap can admit waiters

    # ---- session binding ---------------------------------------------
    def set_group(self, name: str) -> None:
        if name not in self.groups:
            raise ValueError(f'resource group "{name}" does not exist')
        _local.group = name

    def current_group(self) -> str:
        return getattr(_local, "group", DEFAULT_GROUP)

    # ---- admission ----------------------------------------------------
    def _global_cap(self) -> int:
        return int(getattr(self.settings, "resource_group_global_active", 0))

    def _total_active(self) -> int:
        return sum(g.active for g in self.groups.values())

    def _runnable(self, g: ResourceGroup) -> bool:
        if g.concurrency and g.active >= g.concurrency:
            return False
        cap = self._global_cap()
        if cap and self._total_active() >= cap:
            return False
        return True

    def _my_turn(self, g: ResourceGroup) -> bool:
        """Backoff ordering: with a GLOBAL cap configured, the free slot
        goes to the waiter whose group has the least weighted consumed
        chip time — not to whichever thread wakes first; per-group caps
        alone stay FIFO-per-group."""
        if not self._global_cap():
            return True
        nxt = self._next_group()
        return nxt is None or nxt == g.name

    def _eligible(self, g: ResourceGroup) -> bool:
        return self._runnable(g) and self._my_turn(g)

    def _next_group(self) -> str | None:
        """Pick the waiting group with least consumed_s / cpu_weight."""
        best, best_key = None, None
        for g in self.groups.values():
            if not g.waiting:
                continue
            if g.concurrency and g.active >= g.concurrency:
                continue
            key = g.consumed_s / max(g.cpu_weight, 1)
            if best_key is None or key < best_key:
                best, best_key = g.name, key
        return best

    def admit(self, group: str | None = None):
        name = group or self.current_group()
        timeout = float(getattr(self.settings, "resource_queue_timeout_s", 30.0))
        ctx = interrupt.REGISTRY.current()
        with self._cond:
            g = self.groups.get(name)
            if g is None:   # dropped since SET: fall back to default
                g = self.groups[DEFAULT_GROUP]
            if not g.concurrency and not self._global_cap():
                g.admitted_total += 1
                return _GroupSlot(self, g, counted=False)
            if not self._eligible(g):
                # the statement would have to WAIT (no slot, or a slot
                # but not this group's backoff turn — the same predicate
                # the wait loop blocks on): load-shed before joining.
                # Depth counts waiters across ALL groups, since the
                # global cap is what they contend for
                from greengage_tpu.runtime.resqueue import shed_check

                shed_check(self.settings,
                           sum(x.waiting for x in self.groups.values()),
                           "resource group")
            deadline = time.monotonic() + timeout
            g.waiting += 1
            # cancel() from another thread must WAKE this wait, not be
            # discovered at the next timeout slice (same discipline as
            # ResourceQueue.admit)
            waker = None
            if ctx is not None:
                def waker():
                    with self._cond:
                        self._cond.notify_all()
                ctx.add_listener(waker)
            try:
                while not self._eligible(g):
                    if ctx is not None and ctx.cancelled:
                        # leave the wait NOW; re-notify so a release that
                        # raced our abandonment is never lost
                        self._cond.notify_all()
                        counters.inc("queue_cancelled_total")
                        ctx.check()   # raises StatementCancelled
                    remaining = deadline - time.monotonic()
                    if ctx is not None:
                        sr = ctx.remaining()
                        if sr is not None:
                            remaining = min(remaining, sr + 0.001)
                    if remaining <= 0 or not self._cond.wait(remaining):
                        if ctx is not None and ctx.cancelled:
                            continue   # classify at the loop head
                        if self._eligible(g):
                            break
                        if deadline - time.monotonic() > 0:
                            continue   # woken by a cancel-listener ping
                        g.timed_out_total += 1
                        self._cond.notify_all()
                        raise GroupTimeout(
                            f"resource group {g.name}: no slot within "
                            f"{timeout:.0f}s "
                            f"(concurrency={g.concurrency or 'unlimited'})")
            finally:
                g.waiting -= 1
                if waker is not None:
                    ctx.remove_listener(waker)
            g.active += 1
            g.admitted_total += 1
            # wake deferred waiters: our admission changed _next_group()'s
            # answer, and a notify that fired while they held the lock (not
            # yet in wait()) would otherwise be lost until their timeout
            self._cond.notify_all()
            return _GroupSlot(self, g, counted=True)

    def release(self, g: ResourceGroup, elapsed_s: float, counted: bool) -> None:
        with self._cond:
            g.consumed_s += elapsed_s
            if counted:
                g.active -= 1
            self._cond.notify_all()

    def kick(self) -> None:
        """Re-evaluate all waiters (a settings change moved the caps)."""
        with self._cond:
            self._cond.notify_all()

    # ---- observability (gp_toolkit.gp_resgroup_status analog) ---------
    def status(self) -> list[dict]:
        with self._lock:
            return [{
                "name": g.name, "concurrency": g.concurrency,
                "memory_limit_mb": g.memory_limit_mb,
                "cpu_weight": g.cpu_weight, "active": g.active,
                "waiting": g.waiting, "admitted": g.admitted_total,
                "timed_out": g.timed_out_total,
                "chip_seconds": round(g.consumed_s, 3),
            } for g in self.groups.values()]


class _GroupSlot:
    """Context manager holding one admission slot; binds the group's
    memory ceiling to the thread and accounts chip time on release."""

    def __init__(self, mgr: ResourceGroupManager, group: ResourceGroup,
                 counted: bool):
        self.mgr = mgr
        self.group = group
        self.counted = counted
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        _local.mem_limit_mb = self.group.memory_limit_mb
        return self

    def __exit__(self, *a):
        _local.mem_limit_mb = 0
        self.mgr.release(self.group, time.monotonic() - self._t0,
                         self.counted)
        return False
