"""Resource queues — concurrency/memory admission control.

Reference parity: resource queues gate statements before execution
(ResLockPortal, src/backend/utils/resscheduler/resscheduler.c:534) by
active-statement count and cost ceilings; resource groups add per-role
memory shares (src/backend/utils/resgroup/resgroup.c). The TPU-native
translation: the scarce resources are CHIP TIME (one SPMD program runs at
a time per mesh) and HBM, so a queue bounds concurrent mesh statements and
per-query estimated device bytes, and queues excess statements FIFO with a
timeout instead of failing them.

A queued statement is a cancellation point (runtime/interrupt.py): a
cancelled waiter leaves the queue immediately — its cancel() wakes the
wait via a registered listener, and the abandoning waiter re-notifies so
a racing release is never lost (the same discipline as the timeout path).

Overload armor (docs/ROBUSTNESS.md "Overload protection"): with
``admission_queue_limit`` set, a statement that would have to WAIT is
load-shed by ``shed_check`` — a typed, retryable ``AdmissionShed``
(SQLSTATE 53300 analog) at the depth cap, ramping in probabilistically
from ``admission_shed_ramp`` x cap — instead of queueing unboundedly.

Usage (session-level):
    SET resource_queue_active = 2        -- concurrent mesh statements
    SET resource_queue_memory_mb = 4096  -- per-query est ceiling (0 = off)
    SET resource_queue_timeout_s = 30
    SET admission_queue_limit = 8        -- shed past this queue depth
"""

from __future__ import annotations

import random
import threading
import time

from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime.logger import counters


class QueueTimeout(RuntimeError):
    pass


class AdmissionShed(RuntimeError):
    """Typed load-shed rejection (the SQLSTATE 53300 'insufficient
    resources / too many connections' analog): the admission queue is at
    (or ramping toward) its depth cap and this statement was rejected
    instead of queued. Retryable by design — the client should back off
    and retry; the server maps it to a retryable response frame."""

    sqlstate = "53300"
    retryable = True


def shed_check(settings, depth: int, what: str) -> None:
    """Queue-depth load shedding (docs/ROBUSTNESS.md "Overload
    protection"), shared by the resource queue and resource groups.

    ``depth`` is how many statements are ALREADY waiting for a slot; the
    caller invokes this only when the new statement would have to wait.
    At ``admission_queue_limit`` the statement sheds outright; from
    ``admission_shed_ramp`` x the cap upward it sheds probabilistically,
    with probability rising linearly to 1 at the cap — rejection is a
    ramp, not a cliff, so a burst near capacity degrades gradually
    instead of flipping between "everyone queues" and "everyone dies".
    0 disables (legacy queue-forever behavior)."""
    cap = int(getattr(settings, "admission_queue_limit", 0))
    if cap <= 0:
        return
    if depth >= cap:
        counters.inc("admission_shed_total")
        raise AdmissionShed(
            f"statement shed: {what} admission queue is full "
            f"({depth} waiting, admission_queue_limit={cap}); "
            "retry with backoff")
    ramp = min(max(float(getattr(settings, "admission_shed_ramp", 0.75)),
                   0.0), 1.0)
    start = cap * ramp
    if depth > start:
        p = (depth - start) / max(cap - start, 1e-9)
        if random.random() < p:
            counters.inc("admission_shed_total")
            raise AdmissionShed(
                f"statement shed: {what} admission queue depth {depth} "
                f"approaching admission_queue_limit={cap} "
                f"(shed probability {p:.2f}); retry with backoff")


class ResourceQueue:
    """FIFO admission gate for mesh statements."""

    def __init__(self, settings):
        self.settings = settings
        # RLock: add_listener fires the waker INLINE when the flag is
        # already set, on the admitting thread, while it holds this lock
        self._lock = threading.RLock()
        self._slots = threading.Condition(self._lock)
        self.active = 0
        self.waiting = 0
        self.admitted_total = 0
        self.timed_out_total = 0
        self.cancelled_total = 0

    def admit(self):
        """Blocks until a slot frees; raises QueueTimeout once
        resource_queue_timeout_s of TOTAL wait has elapsed (deadline-based:
        wakeups don't restart the clock), or StatementCancelled the moment
        the waiter's statement is cancelled. A waiter abandoning for either
        reason re-notifies so a racing release is never lost."""
        limit = int(self.settings.resource_queue_active)
        ctx = interrupt.REGISTRY.current()
        with self._slots:
            if limit <= 0:
                self.admitted_total += 1
                return _Slot(self, counted=False)
            if self.active >= limit:
                # the statement would have to WAIT: load-shed before
                # joining the queue (admitted statements never shed)
                shed_check(self.settings, self.waiting, "resource queue")
            timeout = float(self.settings.resource_queue_timeout_s)
            deadline = time.monotonic() + timeout
            self.waiting += 1
            # cancel() from another thread must WAKE this wait, not be
            # discovered at the next timeout slice
            waker = None
            if ctx is not None:
                def waker():
                    with self._slots:
                        self._slots.notify_all()
                ctx.add_listener(waker)
            try:
                while self.active >= limit:
                    if ctx is not None and ctx.cancelled:
                        # leave the queue NOW; re-notify so a release
                        # that raced our abandonment is never lost
                        self._slots.notify()
                        self.cancelled_total += 1
                        counters.inc("queue_cancelled_total")
                        ctx.check()   # raises StatementCancelled
                    remaining = deadline - time.monotonic()
                    if ctx is not None:
                        # wake at the statement deadline too, so a
                        # statement_timeout_s shorter than the queue
                        # timeout still fires on time
                        sr = ctx.remaining()
                        if sr is not None:
                            remaining = min(remaining, sr + 0.001)
                    if remaining <= 0 or not self._slots.wait(remaining):
                        if ctx is not None and ctx.cancelled:
                            continue   # classify at the loop head
                        if deadline - time.monotonic() > 0:
                            continue   # woken by a cancel-listener ping
                        # final predicate re-check: a notify that raced our
                        # timeout must not be swallowed
                        if self.active < limit:
                            break
                        self._slots.notify()
                        self.timed_out_total += 1
                        raise QueueTimeout(
                            f"statement timed out after {timeout:.0f}s "
                            f"waiting for a resource queue slot "
                            f"({self.active} active, limit {limit})")
            finally:
                self.waiting -= 1
                if waker is not None:
                    ctx.remove_listener(waker)
            self.active += 1
            self.admitted_total += 1
        return _Slot(self, counted=True)

    def _release(self):
        with self._slots:
            self.active -= 1
            self._slots.notify()

    def stats(self) -> dict:
        return {"active": self.active, "waiting": self.waiting,
                "admitted": self.admitted_total,
                "timed_out": self.timed_out_total,
                "cancelled": self.cancelled_total,
                "limit": int(self.settings.resource_queue_active)}


class _Slot:
    def __init__(self, q: ResourceQueue, counted: bool):
        self._q = q
        self._counted = counted
        self._done = False

    def release(self):
        if self._counted and not self._done:
            self._done = True
            self._q._release()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.release()
