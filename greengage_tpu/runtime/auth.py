"""Client authentication for remote connections — the pg_hba.conf +
auth.c role (/root/reference/src/backend/libpq/auth.c:1,
src/backend/libpq/hba.c).

Model (simplified to the shapes the engine serves):
  - UNIX-socket connections are trusted (local peer — PG's default local
    trust line).
  - TCP connections must authenticate as a user from
    ``<cluster>/gg_hba.json`` via a challenge-response handshake (the
    md5/SCRAM role): the server stores sha256(salt || password); the
    client proves knowledge by returning sha256(nonce || stored_hash)
    for a per-connection nonce — the password never crosses the wire,
    and a replayed proof is useless under a fresh nonce.

``gg useradd`` manages the user file (createuser analog)."""

from __future__ import annotations

import hashlib
import json
import os
import secrets


def _hba_path(cluster_dir: str) -> str:
    return os.path.join(cluster_dir, "gg_hba.json")


def _stored_hash(salt: str, password: str) -> str:
    return hashlib.sha256((salt + password).encode()).hexdigest()


def load_users(cluster_dir: str) -> dict:
    try:
        with open(_hba_path(cluster_dir)) as f:
            return json.load(f).get("users", {})
    except (OSError, ValueError):
        return {}


def add_user(cluster_dir: str, user: str, password: str) -> None:
    path = _hba_path(cluster_dir)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"users": {}}
    salt = secrets.token_hex(8)
    doc.setdefault("users", {})[user] = {
        "salt": salt, "hash": _stored_hash(salt, password)}
    tmp = path + ".tmp"
    # the stored hash IS a login credential under this scheme
    # (pass-the-hash), so the file must be 0600 from its FIRST byte
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def challenge(users: dict, user: str, cluster_dir: str = "") -> dict:
    """Server side: build the handshake challenge. Unknown users get a
    DETERMINISTIC fake salt (HMAC of the username under a per-cluster
    secret — PG's SCRAM mock-authentication) so repeated probes can't
    distinguish real users by salt stability."""
    entry = users.get(user)
    if entry:
        salt = entry["salt"]
    else:
        salt = hashlib.sha256(
            (_cluster_secret(cluster_dir) + ":" + user).encode()
        ).hexdigest()[:16]
    return {"auth": "challenge", "salt": salt,
            "nonce": secrets.token_hex(16)}


def _cluster_secret(cluster_dir: str) -> str:
    """Stable per-cluster secret for mock challenges (created lazily,
    0600)."""
    path = os.path.join(cluster_dir or ".", ".gg_auth_secret")
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        sec = secrets.token_hex(16)
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(sec)
        except OSError:
            pass
        return sec


def prove(salt: str, nonce: str, password: str) -> str:
    """Client side: the proof for a challenge."""
    return hashlib.sha256(
        (nonce + _stored_hash(salt, password)).encode()).hexdigest()


def verify(users: dict, user: str, nonce: str, proof: str) -> bool:
    entry = users.get(user)
    if entry is None:
        return False
    want = hashlib.sha256((nonce + entry["hash"]).encode()).hexdigest()
    return secrets.compare_digest(want, proof)
