"""Statement tracing — the span substrate behind `gg trace` (gpperfmon's
query-detail role, packaged as Chrome ``trace_event`` JSON).

One ``Trace`` is opened per executing statement (keyed by thread, exactly
like the interrupt registry: one server connection = one thread = one
backend) and every host-side phase records a span into it:

    statement
      parse / paramize / plan
      compile                      (XLA trace+compile of a cache miss)
      stage                        (host data path; one child per table)
        stage:<table>
      dispatch                     (device program; multihost: the whole
                                    two-phase exchange, with one child
                                    subtree per worker grafted from its
                                    completion ack)
      fetch / finalize
      spill-pass / spill-merge     (host-offload passes, exec/spill.py)

Spans carry wall-clock-relative start/duration in ms plus a small args
payload (bytes, rows, tiers). Recording one span is two monotonic reads
and one dict append under a lock — cheap enough for every hot path (the
tests bound the overhead at <5% of a warm cached statement).

Worker-side spans ride the multihost control channel: a worker traces its
lockstep execution, exports the span list in its completion ack, and the
coordinator grafts them under its dispatch span (re-based onto the
dispatch span's clock), so one trace shows the whole cluster's statement.

Completed traces land in a bounded ring (``trace_ring_size`` GUC) indexed
by statement id; ``to_chrome()`` renders the ``trace_event`` JSON that
``gg trace <id>`` serves and chrome://tracing / Perfetto load directly.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager

# runaway guards: a pathological statement (thousands of spill passes)
# must degrade to a truncated trace, never to unbounded memory
MAX_SPANS = 4096
MAX_GRAFT_SPANS = 1024

# live device-memory sampler (runtime/memaccount.py installs it): called
# at span boundaries so every span carries its HBM watermark + delta.
# None until installed; the installed sampler returns None on backends
# without allocator stats (CPU), which keeps spans clean there. A hook
# (not an import) so this substrate stays dependency-free.
MEM_SAMPLER = None


def set_mem_sampler(fn) -> None:
    global MEM_SAMPLER
    MEM_SAMPLER = fn

_JSON_SCALARS = (bool, int, float, str, type(None))


def _safe_args(args: dict) -> dict:
    """Coerce a span payload to JSON-safe scalars (numpy ints etc. arrive
    from executor stats)."""
    out = {}
    for k, v in (args or {}).items():
        if isinstance(v, bool) or v is None or isinstance(v, str):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = v
        else:
            try:
                out[k] = v.item()   # numpy scalar
            except Exception:
                out[k] = str(v)
    return out


class Trace:
    """One statement's span tree. Thread-safe: the statement thread, the
    coordinator's ack-collection path, and (via explicit handles) pool
    threads may all record concurrently."""

    def __init__(self, trace_id: int, sql: str):
        self.trace_id = trace_id
        self.sql = (sql or "").strip()[:500]
        self.t0 = time.monotonic()
        self.wall0 = time.time()
        self.dur_ms: float | None = None   # set when the registry retires it
        self.depth = 1                     # nested sql() calls share it
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._spans: list[dict] = []
        self._by_id: dict[int, dict] = {}
        self._stacks: dict[int, list[int]] = {}   # thread ident -> open sids

    # ---- recording -----------------------------------------------------
    def begin(self, name: str, cat: str = "exec", **args) -> int:
        ts = (time.monotonic() - self.t0) * 1e3
        tid = threading.get_ident()
        if MEM_SAMPLER is not None:
            hbm = MEM_SAMPLER()   # device watermark at span entry
            if hbm is not None:
                args["hbm_bytes"] = hbm
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                return -1
            sid = next(self._ids)
            stack = self._stacks.setdefault(tid, [])
            span = {
                "id": sid,
                "parent": stack[-1] if stack else None,
                "name": name,
                "cat": cat,
                "tid": threading.current_thread().name,
                "ts": round(ts, 3),
                "dur": None,
                "args": _safe_args(args),
            }
            self._spans.append(span)
            self._by_id[sid] = span
            stack.append(sid)
        return sid

    def end(self, sid: int, **args) -> None:
        if sid is None or sid < 0:
            return
        now = (time.monotonic() - self.t0) * 1e3
        hbm = MEM_SAMPLER() if MEM_SAMPLER is not None else None
        with self._lock:
            span = self._by_id.get(sid)
            if span is None:
                return
            if hbm is not None:
                # device-memory delta across the span (`gg trace` shows
                # which phase grew/shrank HBM — the data-movement lens)
                span["args"]["hbm_end_bytes"] = hbm
                if "hbm_bytes" in span["args"]:
                    span["args"]["hbm_delta"] = hbm - span["args"]["hbm_bytes"]
            span["dur"] = round(now - span["ts"], 3)
            if args:
                span["args"].update(_safe_args(args))
            stack = self._stacks.get(threading.get_ident())
            if stack and sid in stack:
                del stack[stack.index(sid):]

    def annotate(self, sid: int, **args) -> None:
        """Attach payload to an open (or closed) span after the fact."""
        if sid is None or sid < 0:
            return
        with self._lock:
            span = self._by_id.get(sid)
            if span is not None:
                span["args"].update(_safe_args(args))

    @contextmanager
    def span(self, name: str, cat: str = "exec", **args):
        sid = self.begin(name, cat, **args)
        try:
            yield sid
        finally:
            self.end(sid)

    # ---- introspection -------------------------------------------------
    def open_span(self) -> tuple[str, float] | None:
        """(name, elapsed_ms) of the deepest still-open span — the
        `gg ps` per-statement phase column."""
        now = (time.monotonic() - self.t0) * 1e3
        with self._lock:
            for span in reversed(self._spans):
                if span["dur"] is None:
                    return span["name"], max(now - span["ts"], 0.0)
        return None

    def export(self, limit: int = MAX_SPANS) -> list[dict]:
        """Span records with ts relative to this trace's start (what a
        worker ships in its completion ack). Open spans export with their
        elapsed-so-far duration."""
        now = (time.monotonic() - self.t0) * 1e3
        with self._lock:
            out = []
            for span in self._spans[:limit]:
                s = dict(span)
                s["args"] = dict(span["args"])
                if s["dur"] is None:
                    s["dur"] = round(max(now - s["ts"], 0.0), 3)
                out.append(s)
            return out

    def graft(self, spans: list[dict], parent_sid: int, tid: str,
              base_ms: float | None = None) -> None:
        """Adopt another trace's exported spans as children of
        ``parent_sid`` (the dispatch span), re-based onto its clock —
        or onto an explicit ``base_ms`` offset from THIS trace's start
        (the batched-serving graft, whose donor trace started on its own
        clock rather than inside the parent span)."""
        if not spans:
            return
        with self._lock:
            base = 0.0
            if base_ms is not None:
                base = float(base_ms)
            else:
                pspan = self._by_id.get(parent_sid)
                if pspan is not None:
                    base = pspan["ts"]
            idmap: dict = {}
            for s in spans[:MAX_GRAFT_SPANS]:
                if len(self._spans) >= MAX_SPANS:
                    break
                try:
                    sid = next(self._ids)
                    rec = {
                        "id": sid,
                        "parent": idmap.get(s.get("parent"), parent_sid),
                        "name": str(s.get("name", "?")),
                        "cat": str(s.get("cat", "exec")),
                        "tid": tid,
                        "ts": round(base + float(s.get("ts", 0.0)), 3),
                        "dur": round(float(s.get("dur") or 0.0), 3),
                        "args": _safe_args(s.get("args") or {}),
                    }
                except (TypeError, ValueError):
                    continue   # a garbled span must not lose the trace
                idmap[s.get("id")] = sid
                self._spans.append(rec)
                self._by_id[sid] = rec

    def find_spans(self, name: str) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans if s["name"] == name]


class _NullSpan:
    """Absent-trace stand-in so hot paths can unconditionally `with`."""

    def __enter__(self):
        return -1

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class TraceRegistry:
    """Process-wide registry: in-flight traces keyed by thread (one
    statement per connection thread) plus the bounded completed ring."""

    def __init__(self, ring_size: int = 64):
        self._lock = threading.Lock()
        self._by_thread: dict[int, Trace] = {}
        self._ring: OrderedDict[int, Trace] = OrderedDict()
        self.ring_size = ring_size
        self._ids = itertools.count(1)

    def enter(self, trace_id: int | None, sql: str, enabled: bool = True,
              ring_size: int | None = None) -> tuple[Trace | None, bool]:
        """Open (or re-enter) the calling thread's trace. Nested sql()
        calls share the outermost trace. -> (trace | None, is_outermost);
        None when tracing is disabled and no outer trace exists."""
        if ring_size is not None and ring_size > 0:
            self.ring_size = int(ring_size)
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is not None:
                cur.depth += 1
                return cur, False
            if not enabled:
                return None, True
            tr = Trace(trace_id if trace_id is not None else -next(self._ids),
                       sql)
            self._by_thread[tid] = tr
            return tr, True

    def exit(self, trace: Trace | None) -> None:
        if trace is None:
            return
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is None:
                return
            cur.depth -= 1
            if cur.depth > 0:
                return
            del self._by_thread[tid]
            cur.dur_ms = (time.monotonic() - cur.t0) * 1e3
            self._ring[cur.trace_id] = cur
            while len(self._ring) > max(self.ring_size, 1):
                self._ring.popitem(last=False)

    def current(self) -> Trace | None:
        return self._by_thread.get(threading.get_ident())

    # ---- pipeline-thread adoption (exec/batchserve.py) ---------------
    # The batched-serving pipeline threads are not statement threads:
    # they carry a standalone per-flush Trace so the executor's span
    # calls (module-level span()) land in it while a batch stages or
    # dispatches, and the finished trace is grafted into every member's
    # statement trace + retired into the ring under its own (negative)
    # id, where `gg trace` can serve it directly.
    def adopt(self, trace: Trace) -> None:
        """Make ``trace`` the calling thread's current trace (no nesting
        bookkeeping — pipeline threads adopt exactly one at a time)."""
        with self._lock:
            self._by_thread[threading.get_ident()] = trace

    def release(self, trace: Trace) -> None:
        """Drop the calling thread's adopted trace (only if still it)."""
        tid = threading.get_ident()
        with self._lock:
            if self._by_thread.get(tid) is trace:
                del self._by_thread[tid]

    def retire(self, trace: Trace) -> None:
        """Park a finished standalone trace in the completed ring."""
        with self._lock:
            trace.dur_ms = (time.monotonic() - trace.t0) * 1e3
            self._ring[trace.trace_id] = trace
            while len(self._ring) > max(self.ring_size, 1):
                self._ring.popitem(last=False)

    def get(self, trace_id: int) -> Trace | None:
        """In-flight first (any thread), then the ring."""
        with self._lock:
            for tr in self._by_thread.values():
                if tr.trace_id == trace_id:
                    return tr
            return self._ring.get(trace_id)

    def last(self) -> Trace | None:
        with self._lock:
            if not self._ring:
                return None
            return next(reversed(self._ring.values()))

    def active_span(self, trace_id: int) -> tuple[str, float] | None:
        """(current span name, elapsed ms) of an IN-FLIGHT statement —
        the `gg ps` phase column; None when idle or unknown."""
        with self._lock:
            trs = [t for t in self._by_thread.values()
                   if t.trace_id == trace_id]
        for tr in trs:
            sp = tr.open_span()
            if sp is not None:
                return sp
        return None


TRACES = TraceRegistry()   # process-wide (shmem gpperfmon agent analog)


@contextmanager
def span(name: str, cat: str = "exec", **args):
    """Record a span on the calling thread's current trace; a cheap no-op
    when no trace is open (tracing disabled, untraced worker threads)."""
    tr = TRACES.current()
    if tr is None:
        yield -1
        return
    sid = tr.begin(name, cat, **args)
    try:
        yield sid
    finally:
        tr.end(sid)


def annotate(sid: int, **args) -> None:
    tr = TRACES.current()
    if tr is not None:
        tr.annotate(sid, **args)


def graft_acks(trace: Trace | None, acks, parent_sid: int) -> None:
    """Adopt worker span payloads from multihost completion acks."""
    if trace is None:
        return
    for a in acks or []:
        spans = a.get("spans") if isinstance(a, dict) else None
        if spans:
            trace.graft(spans, parent_sid,
                        tid=f"worker-{a.get('process_id', '?')}")


def to_chrome(trace: Trace) -> dict:
    """Chrome ``trace_event`` JSON (the object form: {"traceEvents": []}).
    Span ids/parents ride in each event's args so tests (and humans) can
    rebuild the tree without duration-containment heuristics."""
    events = []
    tid_ids: dict[str, int] = {}
    for s in trace.export():
        t = tid_ids.setdefault(s["tid"], len(tid_ids) + 1)
        events.append({
            "name": s["name"],
            "cat": s["cat"],
            "ph": "X",
            "ts": round(s["ts"] * 1000.0, 1),        # microseconds
            "dur": round((s["dur"] or 0.0) * 1000.0, 1),
            "pid": 1,
            "tid": t,
            "args": {**s["args"], "span_id": s["id"],
                     "parent": s["parent"]},
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
             "args": {"name": name}} for name, t in tid_ids.items()]
    meta.append({"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": "greengage_tpu"}})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": trace.trace_id,
            "sql": trace.sql,
            "started_unix_s": round(trace.wall0, 3),
            "duration_ms": (None if trace.dur_ms is None
                            else round(trace.dur_ms, 3)),
        },
    }
