"""FTS-lite: probe-based failure detection + mirror promotion.

Reference parity: the FTS bgworker on the master (src/backend/fts/fts.c:123)
polls every primary through a connect/poll/send/receive FSM
(ftsprobe.c:294), marks dead primaries down in gp_segment_configuration,
promotes in-sync mirrors (ftsmessagehandler.c), and bumps an FTS version
that invalidates the dispatcher's topology snapshot.

Here a probe is a tiny device round-trip on the segment's chip (the health
check that matters for a TPU cluster: can the device still execute?) plus a
fault-injection point named "fts_probe" so tests can force failures
(isolation2 fts_errors.sql analog). The prober can run one-shot (tests,
CLI `gg state --probe`) or as a background thread with an interval.
"""

from __future__ import annotations

import threading

import numpy as np

from greengage_tpu.catalog.segments import SegmentConfig, SegmentRole, SegmentStatus
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.logger import counters


class FtsProber:
    def __init__(self, config: SegmentConfig, mesh=None, interval_s: float = 5.0,
                 store=None, on_change=None):
        self.config = config
        self.mesh = mesh
        self.interval_s = interval_s
        self.store = store          # enables the storage-health probe
        self.on_change = on_change  # e.g. catalog save (persist promotions)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.probe_count = 0

    # ---- probe FSM (one cycle over all primaries) ----------------------
    def probe_once(self) -> dict[int, bool]:
        """Probe every primary; returns {content: alive}. Dead primaries
        with an in-sync mirror are promoted (config.mark_down). Sync state
        is recomputed from the durable replication markers first, so a
        stale mirror is never promoted (the gp_stat_replication check)."""
        results: dict[int, bool] = {}
        before = self.config.version
        for entry in self.config.primaries():
            alive = self._probe_segment(entry)
            results[entry.content] = alive
            if not alive and entry.status is SegmentStatus.UP:
                if self.store is not None:
                    from greengage_tpu.runtime.replication import Replicator

                    Replicator(self.store, self.config).refresh_sync_state()
                self.config.mark_down(entry.content)
        self.probe_count += 1
        # coordinator liveness beat (runtime/standby.py): the probe
        # cadence keeps the beat fresh BETWEEN commits, so an idle-but-
        # alive primary is never mistaken for a dead one by the standby
        # watcher — the coordinator heartbeats itself the way it probes
        # its segments
        if self.store is not None:
            from greengage_tpu.runtime import standby as _standby

            if _standby.registered_standby(self.store.root) is not None:
                _standby.primary_beat(self.store.root, self.config.version)
        if self.config.version != before:
            # dispatch consumes the FTS version (mesh re-formation, cached
            # topology invalidation): keep the gauge current on promotion
            counters.set("mh_topology_version", self.config.version)
            if self.on_change is not None:
                try:
                    self.on_change()
                except Exception:
                    pass
        return results

    def _probe_segment(self, entry) -> bool:
        try:
            if faults.check("fts_probe", segment=entry.content):
                return True  # 'skip' = skip the probe, assume alive
            if self.mesh is not None and entry.device_index is not None:
                devices = list(self.mesh.devices.flat)
                if entry.device_index < len(devices):
                    import jax

                    dev = devices[entry.device_index]
                    # minimal execute round-trip on the segment's chip
                    x = jax.device_put(np.ones((1,), np.float32), dev)
                    float(np.asarray(x + 1)[0])
            # storage health: every manifest-referenced file of this
            # content must be present on its acting root (a lost disk is a
            # dead segment even if the chip is fine)
            if self.store is not None and not self.store.storage_ok(entry.content):
                return False
            return True
        except FaultError:
            return False
        except Exception:
            return False

    # ---- background worker (bgworker analog) ---------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            from greengage_tpu.runtime.retry import backoff_delays

            # probe failures back the cadence off (ftsprobe restart
            # backoff) instead of hot-looping a broken probe; a clean
            # cycle restores the configured interval
            delays = None
            wait = self.interval_s
            while not self._stop.wait(wait):
                try:
                    self.probe_once()
                    delays, wait = None, self.interval_s
                except Exception:
                    if delays is None:
                        delays = backoff_delays(base=self.interval_s,
                                                cap=self.interval_s * 8,
                                                jitter=0.25)
                    wait = next(delays)

        self._thread = threading.Thread(target=loop, name="fts-prober", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def cluster_state(config: SegmentConfig) -> list[dict]:
    """gpstate-style rows for every segment entry."""
    out = []
    for e in sorted(config.entries, key=lambda e: (e.content, e.role.value)):
        out.append({
            "content": e.content,
            "role": e.role.value,
            "preferred_role": e.preferred_role.value,
            "status": e.status.value,
            "synced": e.mode_synced,
            "device": e.device_index,
        })
    return out


def needs_rebalance(config: SegmentConfig) -> bool:
    return any(e.role is not e.preferred_role for e in config.entries)
