from greengage_tpu.runtime.faultinject import FaultInjector, faults  # noqa: F401
