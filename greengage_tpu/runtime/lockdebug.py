"""Debug-mode runtime lock-order assertions and the cross-role access
witness — the dynamic halves of the ``gg check`` lock-order and race
analyzers (analysis/lint_locks.py, analysis/lint_races.py).

Two independent hooks, each zero-cost unless armed:

* **Lock order** (``GGTPU_LOCK_DEBUG=1`` / ``enable()``): the static
  analyzer sees the package-wide acquisition graph but must collapse
  per-key lock *families* (``session._table_locks``, the repair locks)
  to one node; this hook watches real acquisitions and fails the
  process on an order inversion the moment one thread observes A -> B
  after any thread observed B -> A — the classic witness a deadlock
  needs, caught even when the interleaving never actually deadlocks.

* **Race witness** (``GGTPU_RACE_DEBUG=1`` / ``enable_races()``):
  ``shared(obj, name)`` wraps a dict-like structure with a proxy that
  records (thread role, held named-lock set, read/write) per access —
  the thread role comes from the spawn site's thread-name prefix
  (analysis/threadmodel.py), the held set from this module's own
  acquisition tracking. The first witnessed pair of accesses from two
  DIFFERENT roles where at least one writes and the held sets share no
  lock raises ``RaceWitnessError`` naming both sides — the runtime
  complement of ``gg check races``, catching an interleaving the
  static model missed (and dumping a JSON report for CI forensics).

Usage::

    from greengage_tpu.runtime import lockdebug
    lock = lockdebug.named(threading.Lock(), "manifest._log_lock")
    cache = lockdebug.shared({}, "manifest._delta_cache")
    with lock: cache[k] = v

``named()``/``shared()`` return their argument unwrapped when the
corresponding mode is off, so production paths keep raw ``threading``
primitives and raw containers.
"""

from __future__ import annotations

import json
import os
import threading


class LockOrderError(AssertionError):
    """Two lock names were observed in both acquisition orders."""


class _OrderTable:
    """Global observed-order relation: pair (a, b) means some thread
    held a while acquiring b. Inversions raise immediately."""

    def __init__(self):
        self._mu = threading.Lock()
        self._after: dict[str, set[str]] = {}
        self._local = threading.local()

    def _held(self) -> list[str]:
        h = getattr(self._local, "held", None)
        if h is None:
            h = self._local.held = []
        return h

    def acquiring(self, name: str) -> None:
        held = self._held()
        # order assertions belong to lock debug; with only the race
        # witness armed this table still tracks the held set (the
        # witness's protection evidence) without judging order
        if _ENABLED:
            with self._mu:
                for outer in held:
                    if outer == name:
                        continue   # re-entrant same-name holds are fine
                    if name in self._after and outer in self._after[name]:
                        raise LockOrderError(
                            f"lock-order inversion: acquiring {name!r} "
                            f"while holding {outer!r}, but {outer!r} was "
                            f"previously acquired while holding {name!r}")
                    self._after.setdefault(outer, set()).add(name)
        held.append(name)

    def released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def reset(self) -> None:
        with self._mu:
            self._after.clear()


_TABLE = _OrderTable()
_ENABLED = bool(int(os.environ.get("GGTPU_LOCK_DEBUG", "0") or "0"))


def enable(on: bool = True) -> None:
    global _ENABLED
    _ENABLED = on
    if not on:
        _TABLE.reset()


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    _TABLE.reset()


class _Named:
    """Order-asserting proxy for Lock/RLock (context-manager protocol +
    acquire/release, which covers every package call pattern)."""

    __slots__ = ("_lock", "_name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self._name = name

    def acquire(self, *a, **kw):
        _TABLE.acquiring(self._name)
        got = self._lock.acquire(*a, **kw)
        if not got:
            _TABLE.released(self._name)
        return got

    def release(self):
        self._lock.release()
        _TABLE.released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *a):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()


def named(lock, name: str):
    """Wrap ``lock`` with order assertions under debug mode; return it
    untouched otherwise. Race debug implies lock debug wrapping: the
    witness's held-set tracking rides the same acquisition hooks."""
    if not (_ENABLED or _RACE_ENABLED):
        return lock
    return _Named(lock, name)


def held_names() -> frozenset:
    """Named locks the calling thread holds right now (the race
    witness's protection evidence)."""
    return frozenset(_TABLE._held())


# ---------------------------------------------------------------------
# cross-role access witness (GGTPU_RACE_DEBUG; docs/ANALYSIS.md)
# ---------------------------------------------------------------------

class RaceWitnessError(AssertionError):
    """Two thread roles touched a shared structure, at least one wrote,
    and the two accesses held no common named lock."""


_RACE_ENABLED = bool(int(os.environ.get("GGTPU_RACE_DEBUG", "0") or "0"))
_RACE_REPORT_PATH = os.environ.get("GGTPU_RACE_REPORT",
                                   "/tmp/gg_race_witness.json")


def enable_races(on: bool = True) -> None:
    global _RACE_ENABLED
    _RACE_ENABLED = on


def races_enabled() -> bool:
    return _RACE_ENABLED


def current_role() -> str:
    """The calling thread's declared role, from its name prefix (every
    package spawn site names its thread — analysis/threadmodel.py)."""
    from greengage_tpu.analysis.threadmodel import role_of_thread_name

    return role_of_thread_name(threading.current_thread().name)


class _Witness:
    """Per-structure access log: one (role, locks, wrote) record per
    distinct observation, checked pairwise against other roles'."""

    __slots__ = ("name", "_mu", "_seen")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._seen: set = set()   # (role, frozenset(locks), wrote)

    def record(self, wrote: bool, op: str) -> None:
        if not _RACE_ENABLED:
            return
        role = current_role()
        locks = held_names()
        rec = (role, locks, wrote)
        with self._mu:
            if rec in self._seen:
                return
            for role2, locks2, wrote2 in self._seen:
                if role2 != role and (wrote or wrote2) \
                        and not (locks & locks2):
                    self._dump(role, locks, wrote, op,
                               role2, locks2, wrote2)
                    raise RaceWitnessError(
                        f"unprotected cross-role access on {self.name!r}: "
                        f"role {role} ({op}, "
                        f"{'write' if wrote else 'read'}, locks "
                        f"{sorted(locks) or 'none'}) vs role {role2} "
                        f"({'write' if wrote2 else 'read'}, locks "
                        f"{sorted(locks2) or 'none'}) — no common lock; "
                        "see gg check races / docs/ANALYSIS.md")
            self._seen.add(rec)

    def _dump(self, role, locks, wrote, op, role2, locks2, wrote2) -> None:
        """Forensics file for the CI artifact: the witnessed pair, the
        structure, and the offending thread's identity."""
        try:
            with open(_RACE_REPORT_PATH, "w") as f:
                json.dump({
                    "structure": self.name,
                    "thread": threading.current_thread().name,
                    "access": {"role": role, "op": op, "write": wrote,
                               "locks": sorted(locks)},
                    "prior": {"role": role2, "write": wrote2,
                              "locks": sorted(locks2)},
                }, f, indent=1, sort_keys=True)
        except OSError:
            pass


# dict/OrderedDict surface split by effect; everything else a structure
# needs should be added here, not reached through __getattr__ silently
_READ_METHODS = ("get", "keys", "values", "items", "copy")
_WRITE_METHODS = ("pop", "popitem", "clear", "update", "setdefault",
                  "move_to_end")


class SharedDict:
    """Access-witnessing proxy over a dict-like structure. Mirrors the
    mapping surface the package uses; every entry point records
    (role, held locks) before delegating."""

    __slots__ = ("_d", "_w")

    def __init__(self, d, name: str):
        self._d = d
        self._w = _Witness(name)

    # -- reads ----------------------------------------------------------
    def __getitem__(self, k):
        self._w.record(False, "__getitem__")
        return self._d[k]

    def __contains__(self, k):
        self._w.record(False, "__contains__")
        return k in self._d

    def __len__(self):
        self._w.record(False, "__len__")
        return len(self._d)

    def __iter__(self):
        self._w.record(False, "__iter__")
        return iter(self._d)

    def __bool__(self):
        self._w.record(False, "__bool__")
        return bool(self._d)

    # -- writes ---------------------------------------------------------
    def __setitem__(self, k, v):
        self._w.record(True, "__setitem__")
        self._d[k] = v

    def __delitem__(self, k):
        self._w.record(True, "__delitem__")
        del self._d[k]

    def __getattr__(self, name):
        if name in _READ_METHODS:
            self._w.record(False, name)
        elif name in _WRITE_METHODS:
            self._w.record(True, name)
        else:
            raise AttributeError(
                f"{type(self._d).__name__} witness proxy does not expose "
                f"{name!r}; add it to lockdebug.SharedDict explicitly")
        return getattr(self._d, name)


def shared(obj, name: str):
    """Wrap a dict-like shared structure with the access witness under
    ``GGTPU_RACE_DEBUG``; return it untouched otherwise."""
    if not _RACE_ENABLED:
        return obj
    return SharedDict(obj, name)
