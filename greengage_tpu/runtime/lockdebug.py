"""Debug-mode runtime lock-order assertions — the dynamic half of the
``gg check`` lock-order analyzer (analysis/lint_locks.py).

The static analyzer sees the package-wide acquisition graph but must
collapse per-key lock *families* (``session._table_locks``, the repair
locks) to one node; this hook watches real acquisitions and fails the
process on an order inversion the moment one thread observes A -> B
after any thread observed B -> A — the classic witness a deadlock needs,
caught even when the interleaving never actually deadlocks.

Zero-cost by default: nothing records unless ``enable()`` ran (tests,
``GGTPU_LOCK_DEBUG=1``). Usage::

    from greengage_tpu.runtime import lockdebug
    lock = lockdebug.named(threading.Lock(), "manifest._log_lock")
    with lock: ...

``named()`` returns the lock unwrapped when disabled, so production
paths keep raw ``threading`` primitives.
"""

from __future__ import annotations

import os
import threading


class LockOrderError(AssertionError):
    """Two lock names were observed in both acquisition orders."""


class _OrderTable:
    """Global observed-order relation: pair (a, b) means some thread
    held a while acquiring b. Inversions raise immediately."""

    def __init__(self):
        self._mu = threading.Lock()
        self._after: dict[str, set[str]] = {}
        self._local = threading.local()

    def _held(self) -> list[str]:
        h = getattr(self._local, "held", None)
        if h is None:
            h = self._local.held = []
        return h

    def acquiring(self, name: str) -> None:
        held = self._held()
        with self._mu:
            for outer in held:
                if outer == name:
                    continue   # re-entrant same-name holds are fine
                if name in self._after and outer in self._after[name]:
                    raise LockOrderError(
                        f"lock-order inversion: acquiring {name!r} while "
                        f"holding {outer!r}, but {outer!r} was previously "
                        f"acquired while holding {name!r}")
                self._after.setdefault(outer, set()).add(name)
        held.append(name)

    def released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def reset(self) -> None:
        with self._mu:
            self._after.clear()


_TABLE = _OrderTable()
_ENABLED = bool(int(os.environ.get("GGTPU_LOCK_DEBUG", "0") or "0"))


def enable(on: bool = True) -> None:
    global _ENABLED
    _ENABLED = on
    if not on:
        _TABLE.reset()


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    _TABLE.reset()


class _Named:
    """Order-asserting proxy for Lock/RLock (context-manager protocol +
    acquire/release, which covers every package call pattern)."""

    __slots__ = ("_lock", "_name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self._name = name

    def acquire(self, *a, **kw):
        _TABLE.acquiring(self._name)
        got = self._lock.acquire(*a, **kw)
        if not got:
            _TABLE.released(self._name)
        return got

    def release(self):
        self._lock.release()
        _TABLE.released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *a):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()


def named(lock, name: str):
    """Wrap ``lock`` with order assertions under debug mode; return it
    untouched otherwise."""
    if not _ENABLED:
        return lock
    return _Named(lock, name)
