"""Parallel file-distribution ingest + single-row error handling.

Reference parity:
  * gpfdist (src/bin/gpfdist/gpfdist.c): a standalone HTTP server that
    hands out DISJOINT newline-aligned chunks of a file so many loaders
    pull in parallel. FileDistServer implements the chunk protocol
    (``GET /rel/path?chunk=i&nchunks=N``); chunk boundaries snap forward
    to the next newline so every row belongs to exactly one chunk.
  * SREH (src/backend/cdb/cdbsreh.c): ``SEGMENT REJECT LIMIT`` semantics —
    malformed rows are collected into an error log instead of aborting the
    whole load, up to a limit. parse_csv_rows returns (rows, rejects);
    the session layer enforces the limit and appends rejects to
    ``<cluster>/errlog/<table>.jsonl`` (the gp_read_error_log analog).
  * Streaming ingest plane (docs/ROBUSTNESS.md "Write-intent commit &
    streaming ingest"): StreamIngestor/StreamSession — long-lived COPY
    FROM STDIN-style sessions that buffer rows host-side (bounded) and
    commit micro-batches through the manifest's write-intent path on
    size/time watermarks, with brownout admission, typed retryable
    sheds, and idempotent resume from the last committed batch sequence
    (the Taurus-style near-storage continuous-ingest shape).
"""

from __future__ import annotations

import contextlib
import http.server
import json
import os
import socketserver
import threading
import urllib.parse
import urllib.request
import uuid
import csv as _csv
import io
import time

import numpy as np

from greengage_tpu.runtime import lockdebug
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.overload import CONTROLLER
from greengage_tpu.runtime.resqueue import AdmissionShed


# ---------------------------------------------------------------------------
# gpfdist-lite server
# ---------------------------------------------------------------------------

class FileDistServer:
    """HTTP chunk server over a directory of load files."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        self.root = os.path.abspath(root)
        self.host = host
        self.port = port
        self._server = None
        self._thread = None
        self.requests_served = 0

    def url(self, relpath: str) -> str:
        return f"gpfdist://{self.host}:{self.port}/{relpath}"

    def start(self) -> None:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                outer.requests_served += 1
                parsed = urllib.parse.urlparse(self.path)
                rel = urllib.parse.unquote(parsed.path).lstrip("/")
                full = os.path.abspath(os.path.join(outer.root, rel))
                if not full.startswith(outer.root + os.sep) \
                        and full != outer.root:
                    self.send_error(403)
                    return
                if not os.path.isfile(full):
                    self.send_error(404)
                    return
                q = urllib.parse.parse_qs(parsed.query)
                try:
                    data = _read_chunk(
                        full,
                        int(q.get("chunk", ["0"])[0]),
                        int(q.get("nchunks", ["1"])[0]))
                except ValueError:
                    self.send_error(400)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/csv")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gg-gpfdist", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def _newline_after(f, pos: int, size: int) -> int:
    """First offset AFTER the next newline at/after pos (size if none)."""
    if pos <= 0:
        return 0
    if pos >= size:
        return size
    f.seek(pos)
    while True:
        buf = f.read(1 << 16)
        if not buf:
            return size
        i = buf.find(b"\n")
        if i >= 0:
            return pos + i + 1
        pos += len(buf)


def _read_chunk(path: str, chunk: int, nchunks: int) -> bytes:
    """Newline-aligned chunk: [align(i*size/N), align((i+1)*size/N))."""
    if not (0 <= chunk < nchunks):
        raise ValueError("chunk out of range")
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        start = _newline_after(f, chunk * size // nchunks, size)
        end = _newline_after(f, (chunk + 1) * size // nchunks, size)
        f.seek(start)
        return f.read(end - start)


def fetch_chunks(url: str, nchunks: int) -> list[bytes]:
    """Pull all chunks of a gpfdist:// URL concurrently (the parallel
    external-table scan role — every segment fetches disjoint slices)."""
    http_url = "http://" + url[len("gpfdist://"):]
    out: list = [None] * nchunks
    errs: list = []

    def one(i):
        try:
            with urllib.request.urlopen(
                    f"{http_url}?chunk={i}&nchunks={nchunks}") as r:
                out[i] = r.read()
        except Exception as e:
            errs.append(e)

    # named so the runtime race witness tags these as the ingest role
    # (threadmodel.ROLE_NAME_PREFIXES maps the gg-gpfdist prefix)
    ts = [threading.Thread(target=one, args=(i,),
                           name=f"gg-gpfdist-fetch-{i}")
          for i in range(nchunks)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    if errs:
        raise IOError(f"gpfdist fetch failed: {errs[0]}")
    return out


# ---------------------------------------------------------------------------
# SREH CSV parsing
# ---------------------------------------------------------------------------

def parse_csv_rows(text: str, schema, delim: str, header: bool, null_s: str,
                   line_base: int = 0):
    """-> (cols {name: list}, valids {name: list}, rejects [(line, raw,
    error)]). Malformed rows are REJECTED, not fatal (cdbsreh.c role) —
    the caller enforces the reject limit."""

    from greengage_tpu import types as T

    cols = {c.name: [] for c in schema.columns}
    valids = {c.name: [] for c in schema.columns}
    rejects = []
    rd = _csv.reader(io.StringIO(text), delimiter=delim)
    for i, row in enumerate(rd):
        if header and i == 0:
            continue
        if not row:
            continue
        if len(row) != len(schema.columns):
            rejects.append((line_base + i + 1, delim.join(row),
                            f"expected {len(schema.columns)} columns, "
                            f"got {len(row)}"))
            continue
        parsed_vals = []
        parsed_valid = []
        err = None
        for c, v in zip(schema.columns, row):
            if v == null_s:
                parsed_vals.append(_zero_for(c.type))
                parsed_valid.append(False)
                continue
            try:
                parsed_vals.append(T.from_string(v, c.type))
                parsed_valid.append(True)
            except (ValueError, TypeError, ArithmeticError) as e:
                err = f'column "{c.name}": {e}'
                break
        if err is not None:
            rejects.append((line_base + i + 1, delim.join(row), err))
            continue
        for c, v, ok in zip(schema.columns, parsed_vals, parsed_valid):
            cols[c.name].append(v)
            valids[c.name].append(ok)
    return cols, valids, rejects


def _zero_for(t):
    from greengage_tpu import types as T

    if t.kind is T.Kind.TEXT:
        return ""
    if t.kind is T.Kind.FLOAT64:
        return 0.0
    if t.kind is T.Kind.BOOL:
        return False
    return 0


# ---------------------------------------------------------------------------
# error log (gp_read_error_log analog)
# ---------------------------------------------------------------------------

def append_error_log(root: str, table: str, rejects: list) -> None:
    d = os.path.join(root, "errlog")
    os.makedirs(d, exist_ok=True)

    with open(os.path.join(d, f"{table}.jsonl"), "a") as f:
        for line, raw, err in rejects:
            f.write(json.dumps({"ts": time.time(), "line": line,
                                "row": raw, "error": err}) + "\n")


def read_error_log(root: str, table: str) -> list[dict]:
    p = os.path.join(root, "errlog", f"{table}.jsonl")
    if not os.path.exists(p):
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# streaming ingest plane (crash-safe micro-batch COPY)
# ---------------------------------------------------------------------------

class StreamSession:
    """One long-lived ingest stream onto one table.

    Durability contract (the resume protocol): a batch is ACKED when
    buffered (volatile) and COMMITTED when its micro-batch's merge line
    is durable — the batch sequence rides the commit record as the
    stream's watermark ("s" entry), so after kill-9 the client re-begins
    with the same stream id, reads resume_seq, and re-sends everything
    above it; replayed batches at/below the committed watermark are
    deduplicated here (ingest_resume_dedup_total). Nothing past the last
    committed watermark survives a crash, and nothing at/below it is
    ever applied twice.

    Shared across the serving handler threads (feed/finish) and the
    gg-ingest-flush deadline thread (tick) — every mutable attribute is
    guarded by self._mu (gg check races)."""

    def __init__(self, db, stream_id: str, table: str, committed_seq: int):
        self._db = db                    # read-only after construction
        self._mu = lockdebug.named(threading.Lock(),
                                   "ingest.StreamSession._mu")
        self.id = stream_id
        self.table = table
        self.committed_seq = int(committed_seq)   # durable watermark
        self.acked_seq = int(committed_seq)       # buffered, volatile
        self.batches: list = []       # [(seq, {col: [values]}, nrows)]
        self.buffered_rows = 0
        self.first_ts: float | None = None        # oldest buffered batch
        self.last_activity = time.monotonic()
        self.closed = False
        self.error: str | None = None

    # -- client surface --------------------------------------------------
    def feed(self, columns: dict, seq: int) -> dict:
        """Buffer one client batch; flush inline when a watermark trips.
        Returns the ack frame ({seq, acked_seq, committed_seq, ...})."""
        seq = int(seq)
        lens = {k: len(v) for k, v in columns.items()}
        if not lens:
            raise ValueError("empty batch: no columns")
        n = next(iter(lens.values()))
        if any(l != n for l in lens.values()):
            raise ValueError(f"ragged batch: column lengths {lens}")
        settings = self._db.settings
        with self._mu:
            self.last_activity = time.monotonic()
            if self.error is not None:
                raise RuntimeError(
                    f"stream {self.id} failed: {self.error} — re-begin "
                    "and resume from the committed watermark")
            if self.closed:
                raise RuntimeError(f"stream {self.id} is closed")
            if seq <= self.acked_seq:
                # resume replay (or a client retry of an acked frame)
                counters.inc("ingest_resume_dedup_total")
                return {"stream": self.id, "seq": seq, "duplicate": True,
                        "acked_seq": self.acked_seq,
                        "committed_seq": self.committed_seq}
            # admission: sustained overload degrades to typed retryable
            # sheds (PR-15 armor), never to unbounded host buffering
            CONTROLLER.evaluate(settings)
            if CONTROLLER.brownout_active():
                counters.inc("ingest_shed_total")
                raise AdmissionShed(
                    "stream batch shed: memory brownout; retry with "
                    "backoff")
            cap = max(1, int(getattr(settings, "ingest_buffer_rows",
                                     65536)))
            if self.buffered_rows + n > cap and self.batches:
                self._flush_locked()    # make room: flush IS backpressure
            if self.buffered_rows + n > cap:
                counters.inc("ingest_shed_total")
                raise AdmissionShed(
                    f"stream batch of {n} rows exceeds "
                    f"ingest_buffer_rows={cap}; split the batch")
            self.batches.append(
                (seq, {k: list(v) for k, v in columns.items()}, n))
            self.buffered_rows += n
            self.acked_seq = seq
            if self.first_ts is None:
                self.first_ts = time.monotonic()
            if self.buffered_rows >= max(1, int(getattr(
                    settings, "ingest_batch_rows", 4096))):
                self._flush_locked()    # size watermark
            return {"stream": self.id, "seq": seq,
                    "acked_seq": self.acked_seq,
                    "committed_seq": self.committed_seq,
                    "buffered_rows": self.buffered_rows}

    def finish(self, drain: bool = True) -> dict:
        """Close the stream: final flush (drain=True) or drop the buffer.
        Returns the final watermark frame."""
        with self._mu:
            if drain and self.error is None and not self.closed:
                self._flush_locked()
            self.batches = []
            self.buffered_rows = 0
            self.first_ts = None
            self.closed = True
            return {"stream": self.id, "table": self.table,
                    "committed_seq": self.committed_seq,
                    "error": self.error}

    # -- flusher surface -------------------------------------------------
    def tick(self, now: float, settings) -> bool:
        """Deadline maintenance (gg-ingest-flush cadence): flush when the
        time watermark expires; returns True when the stream idled past
        ingest_stream_idle_s and was closed (caller deregisters it)."""
        with self._mu:
            if self.closed:
                return True
            if self.batches and self.first_ts is not None \
                    and self.error is None:
                batch_ms = float(getattr(settings, "ingest_batch_ms",
                                         250.0))
                if (now - self.first_ts) * 1000.0 >= batch_ms:
                    try:
                        self._flush_locked()    # time watermark
                    except Exception:
                        # a fault parked at ingest_flush raises BEFORE
                        # the buffer drains, so that flush re-tries next
                        # tick; an insert failure has already drained the
                        # buffer and set self.error — the stream is
                        # terminally failed and the client must re-begin
                        # and resume above the committed watermark
                        pass
            idle_s = float(getattr(settings, "ingest_stream_idle_s",
                                   300.0))
            if idle_s > 0 and now - self.last_activity >= idle_s:
                if self.error is None:
                    with contextlib.suppress(Exception):
                        self._flush_locked()
                self.batches = []
                self.buffered_rows = 0
                self.closed = True
                return True
            return False

    def rows_buffered(self) -> int:
        with self._mu:
            return self.buffered_rows

    def status_row(self) -> dict:
        with self._mu:
            return {"stream": self.id, "table": self.table,
                    "buffered_rows": self.buffered_rows,
                    "acked_seq": self.acked_seq,
                    "committed_seq": self.committed_seq,
                    "closed": self.closed, "error": self.error}

    # -- internals -------------------------------------------------------
    def _flush_locked(self) -> None:
        """Commit the buffered batches as ONE micro-batch through the
        write-intent path. Caller holds self._mu (per-stream flushes are
        serialized — the protocol's ordering unit is the stream)."""
        if not self.batches:
            return
        db = self._db
        # the mid-stream kill window: parked HERE the buffer is intact
        # and nothing past committed_seq is durable
        faults.check("ingest_flush")
        batches, self.batches = self.batches, []
        rows, self.buffered_rows = self.buffered_rows, 0
        self.first_ts = None
        maxseq = max(s for s, _c, _n in batches)
        try:
            schema = db.catalog.get(self.table)
            cols: dict = {}
            valids: dict = {}
            for c in schema.columns:
                vals: list = []
                oks: list = []
                for _s, payload, _n in batches:
                    if c.name not in payload:
                        raise ValueError(
                            f"batch missing column {c.name!r}")
                    for v in payload[c.name]:
                        if v is None:
                            vals.append(_zero_for(c.type))
                            oks.append(False)
                        else:
                            vals.append(v)
                            oks.append(True)
                cols[c.name] = vals
                if not all(oks):
                    valids[c.name] = np.asarray(oks, dtype=bool)
            with contextlib.ExitStack() as st:
                # same lock discipline as an autocommit INSERT statement:
                # shared session write mode, plus the per-table serializer
                # only when the table's dictionary encoding needs it
                st.enter_context(db._write_lock.shared())
                if db._append_needs_table_lock(self.table):
                    st.enter_context(db._table_lock(self.table))
                db.store.insert(self.table, cols, valids or None,
                                stream_marks={self.id: maxseq})
                db._post_commit()   # archive/standby/replicator ride-along
        except BaseException as e:
            # the drained batches are gone from the buffer: fail the
            # SESSION so the client re-begins and resends everything
            # above committed_seq — exactly what resume replays
            self.error = f"{type(e).__name__}: {e}"
            raise
        self.committed_seq = max(self.committed_seq, maxseq)
        counters.inc("ingest_batches_total")
        counters.inc("ingest_rows_total", rows)


class StreamIngestor:
    """Registry + deadline flusher for the streaming ingest plane. One
    per Database; the gg-ingest-flush thread only exists while streams
    are open. Shared across handler threads and the flusher — the
    registry dict and lifecycle flags are guarded by self._mu."""

    def __init__(self, db):
        self._db = db               # read-only after construction
        self._mu = lockdebug.named(threading.Lock(),
                                   "ingest.StreamIngestor._mu")
        self._streams: dict[str, StreamSession] = {}
        self._flusher: threading.Thread | None = None
        self._wake = threading.Event()      # set = flusher exits
        self._stopped = False

    # -- wire surface (runtime/server.py _control ops) -------------------
    def stream_begin(self, table: str, stream_id: str | None = None) -> dict:
        """Open (or resume) a stream; returns {stream, table, resume_seq}.
        resume_seq is the durable watermark — the client re-sends batch
        sequences ABOVE it after a crash or reconnect."""
        db = self._db
        CONTROLLER.evaluate(db.settings)
        if CONTROLLER.brownout_active():
            counters.inc("ingest_shed_total")
            raise AdmissionShed(
                "stream admission shed: memory brownout; retry with "
                "backoff")
        if table not in db.catalog:
            raise ValueError(f"unknown table {table!r}")
        schema = db.catalog.get(table)
        if getattr(schema, "partitions", None):
            raise ValueError(
                "stream ingest targets a plain (non-partitioned) table")
        sid = str(stream_id) if stream_id else uuid.uuid4().hex[:12]
        with self._mu:
            if self._stopped:
                raise RuntimeError("ingest plane is shut down")
            old = self._streams.pop(sid, None)
        if old is not None:
            # live reconnect: quiesce the old session BEFORE reading the
            # resume watermark. finish(drain=False) serializes behind an
            # in-flight deadline flush via the session lock, so a commit
            # racing this re-begin lands before the snapshot below and
            # the client never gets a resume_seq under what is durable.
            # The dropped unacked buffer is exactly what it resends.
            with contextlib.suppress(Exception):
                old.finish(drain=False)
        snap = db.store.manifest.snapshot()
        committed = int(snap["tables"].get(table, {})
                        .get("streams", {}).get(sid, 0))
        sess = StreamSession(db, sid, table, committed)
        with self._mu:
            if self._stopped:
                raise RuntimeError("ingest plane is shut down")
            self._streams[sid] = sess
            self._ensure_flusher_locked()
            n = len(self._streams)
        counters.set("ingest_active_streams", n)
        return {"stream": sid, "table": table, "resume_seq": committed}

    def stream_rows(self, stream_id: str, columns: dict, seq: int) -> dict:
        sess = self._get(stream_id)
        try:
            return sess.feed(columns, seq)
        finally:
            self._refresh_gauges()

    def stream_end(self, stream_id: str) -> dict:
        sess = self._get(stream_id)
        try:
            out = sess.finish()
        finally:
            with self._mu:
                self._streams.pop(stream_id, None)
                n = len(self._streams)
            counters.set("ingest_active_streams", n)
            self._refresh_gauges()
        return out

    def stream_status(self) -> list[dict]:
        with self._mu:
            sessions = list(self._streams.values())
        return [s.status_row() for s in sessions]

    # -- lifecycle -------------------------------------------------------
    def drain_all(self, drain: bool = True) -> int:
        """Flush-or-abort every open stream (server/database shutdown):
        no abandoned buffers. Returns the number of streams closed."""
        with self._mu:
            sessions, self._streams = dict(self._streams), {}
        for sess in sessions.values():
            with contextlib.suppress(Exception):
                sess.finish(drain=drain)
        counters.set("ingest_active_streams", 0)
        counters.set("ingest_buffered_rows", 0)
        return len(sessions)

    def stop(self, drain: bool = True) -> None:
        """Shut the plane down: drain streams, stop the flusher with a
        bounded join (it wakes immediately off the event)."""
        with self._mu:
            self._stopped = True
            flusher, self._flusher = self._flusher, None
        self._wake.set()
        self.drain_all(drain=drain)
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout=10.0)

    # -- internals -------------------------------------------------------
    def _get(self, stream_id: str) -> StreamSession:
        with self._mu:
            sess = self._streams.get(str(stream_id))
        if sess is None:
            raise ValueError(
                f"unknown stream {stream_id!r}: begin a stream first "
                "(after a crash, re-begin with the same id and resume "
                "from resume_seq)")
        return sess

    def _refresh_gauges(self) -> None:
        with self._mu:
            sessions = list(self._streams.values())
        counters.set("ingest_buffered_rows",
                     sum(s.rows_buffered() for s in sessions))

    def _ensure_flusher_locked(self) -> None:
        if self._flusher is not None and self._flusher.is_alive():
            return
        # threading.Event is internally locked; the flusher's unlocked
        # wait() observing a clear()/set() is the designed wakeup channel
        self._wake.clear()   # gg:ok(races)
        t = threading.Thread(target=self._flush_loop,
                             name="gg-ingest-flush", daemon=True)
        self._flusher = t
        t.start()

    def _flush_loop(self) -> None:
        """Deadline flusher: trips time watermarks and idle deadlines at
        half the batch_ms cadence; exits when stop() sets the event."""
        while True:
            settings = self._db.settings
            tick_s = max(0.02, min(1.0, float(getattr(
                settings, "ingest_batch_ms", 250.0)) / 2000.0))
            if self._wake.wait(tick_s):
                return
            now = time.monotonic()
            with self._mu:
                sessions = list(self._streams.items())
            expired = [(sid, sess) for sid, sess in sessions
                       if sess.tick(now, settings)]
            if expired:
                with self._mu:
                    for sid, sess in expired:
                        # identity-guarded: a re-begin may have swapped in
                        # a fresh session for this id since the snapshot
                        if self._streams.get(sid) is sess:
                            self._streams.pop(sid)
                    n = len(self._streams)
                counters.set("ingest_active_streams", n)
            self._refresh_gauges()
