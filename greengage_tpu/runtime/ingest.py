"""Parallel file-distribution ingest + single-row error handling.

Reference parity:
  * gpfdist (src/bin/gpfdist/gpfdist.c): a standalone HTTP server that
    hands out DISJOINT newline-aligned chunks of a file so many loaders
    pull in parallel. FileDistServer implements the chunk protocol
    (``GET /rel/path?chunk=i&nchunks=N``); chunk boundaries snap forward
    to the next newline so every row belongs to exactly one chunk.
  * SREH (src/backend/cdb/cdbsreh.c): ``SEGMENT REJECT LIMIT`` semantics —
    malformed rows are collected into an error log instead of aborting the
    whole load, up to a limit. parse_csv_rows returns (rows, rejects);
    the session layer enforces the limit and appends rejects to
    ``<cluster>/errlog/<table>.jsonl`` (the gp_read_error_log analog).
"""

from __future__ import annotations

import http.server
import json
import os
import socketserver
import threading
import urllib.parse
import urllib.request
import csv as _csv
import io
import time


# ---------------------------------------------------------------------------
# gpfdist-lite server
# ---------------------------------------------------------------------------

class FileDistServer:
    """HTTP chunk server over a directory of load files."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        self.root = os.path.abspath(root)
        self.host = host
        self.port = port
        self._server = None
        self._thread = None
        self.requests_served = 0

    def url(self, relpath: str) -> str:
        return f"gpfdist://{self.host}:{self.port}/{relpath}"

    def start(self) -> None:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                outer.requests_served += 1
                parsed = urllib.parse.urlparse(self.path)
                rel = urllib.parse.unquote(parsed.path).lstrip("/")
                full = os.path.abspath(os.path.join(outer.root, rel))
                if not full.startswith(outer.root + os.sep) \
                        and full != outer.root:
                    self.send_error(403)
                    return
                if not os.path.isfile(full):
                    self.send_error(404)
                    return
                q = urllib.parse.parse_qs(parsed.query)
                try:
                    data = _read_chunk(
                        full,
                        int(q.get("chunk", ["0"])[0]),
                        int(q.get("nchunks", ["1"])[0]))
                except ValueError:
                    self.send_error(400)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/csv")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gg-gpfdist", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def _newline_after(f, pos: int, size: int) -> int:
    """First offset AFTER the next newline at/after pos (size if none)."""
    if pos <= 0:
        return 0
    if pos >= size:
        return size
    f.seek(pos)
    while True:
        buf = f.read(1 << 16)
        if not buf:
            return size
        i = buf.find(b"\n")
        if i >= 0:
            return pos + i + 1
        pos += len(buf)


def _read_chunk(path: str, chunk: int, nchunks: int) -> bytes:
    """Newline-aligned chunk: [align(i*size/N), align((i+1)*size/N))."""
    if not (0 <= chunk < nchunks):
        raise ValueError("chunk out of range")
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        start = _newline_after(f, chunk * size // nchunks, size)
        end = _newline_after(f, (chunk + 1) * size // nchunks, size)
        f.seek(start)
        return f.read(end - start)


def fetch_chunks(url: str, nchunks: int) -> list[bytes]:
    """Pull all chunks of a gpfdist:// URL concurrently (the parallel
    external-table scan role — every segment fetches disjoint slices)."""
    http_url = "http://" + url[len("gpfdist://"):]
    out: list = [None] * nchunks
    errs: list = []

    def one(i):
        try:
            with urllib.request.urlopen(
                    f"{http_url}?chunk={i}&nchunks={nchunks}") as r:
                out[i] = r.read()
        except Exception as e:
            errs.append(e)

    # named so the runtime race witness tags these as the ingest role
    # (threadmodel.ROLE_NAME_PREFIXES maps the gg-gpfdist prefix)
    ts = [threading.Thread(target=one, args=(i,),
                           name=f"gg-gpfdist-fetch-{i}")
          for i in range(nchunks)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    if errs:
        raise IOError(f"gpfdist fetch failed: {errs[0]}")
    return out


# ---------------------------------------------------------------------------
# SREH CSV parsing
# ---------------------------------------------------------------------------

def parse_csv_rows(text: str, schema, delim: str, header: bool, null_s: str,
                   line_base: int = 0):
    """-> (cols {name: list}, valids {name: list}, rejects [(line, raw,
    error)]). Malformed rows are REJECTED, not fatal (cdbsreh.c role) —
    the caller enforces the reject limit."""

    from greengage_tpu import types as T

    cols = {c.name: [] for c in schema.columns}
    valids = {c.name: [] for c in schema.columns}
    rejects = []
    rd = _csv.reader(io.StringIO(text), delimiter=delim)
    for i, row in enumerate(rd):
        if header and i == 0:
            continue
        if not row:
            continue
        if len(row) != len(schema.columns):
            rejects.append((line_base + i + 1, delim.join(row),
                            f"expected {len(schema.columns)} columns, "
                            f"got {len(row)}"))
            continue
        parsed_vals = []
        parsed_valid = []
        err = None
        for c, v in zip(schema.columns, row):
            if v == null_s:
                parsed_vals.append(_zero_for(c.type))
                parsed_valid.append(False)
                continue
            try:
                parsed_vals.append(T.from_string(v, c.type))
                parsed_valid.append(True)
            except (ValueError, TypeError, ArithmeticError) as e:
                err = f'column "{c.name}": {e}'
                break
        if err is not None:
            rejects.append((line_base + i + 1, delim.join(row), err))
            continue
        for c, v, ok in zip(schema.columns, parsed_vals, parsed_valid):
            cols[c.name].append(v)
            valids[c.name].append(ok)
    return cols, valids, rejects


def _zero_for(t):
    from greengage_tpu import types as T

    if t.kind is T.Kind.TEXT:
        return ""
    if t.kind is T.Kind.FLOAT64:
        return 0.0
    if t.kind is T.Kind.BOOL:
        return False
    return 0


# ---------------------------------------------------------------------------
# error log (gp_read_error_log analog)
# ---------------------------------------------------------------------------

def append_error_log(root: str, table: str, rejects: list) -> None:
    d = os.path.join(root, "errlog")
    os.makedirs(d, exist_ok=True)

    with open(os.path.join(d, f"{table}.jsonl"), "a") as f:
        for line, raw, err in rejects:
            f.write(json.dumps({"ts": time.time(), "line": line,
                                "row": raw, "error": err}) + "\n")


def read_error_log(root: str, table: str) -> list[dict]:
    p = os.path.join(root, "errlog", f"{table}.jsonl")
    if not os.path.exists(p):
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
