"""Master standby — the gpinitstandby / gpactivatestandby analog
(reference: gpMgmt/bin/gpinitstandby:1, gpactivatestandby:1).

The coordinator's durable state is small and file-shaped: catalog.json
(schemas/topology/stats), manifest.json (the distributed commit record),
append-only dictionary files, and calibration.json. A standby is a
directory holding a continuously-synced copy of exactly that state:
``init_standby`` seeds it, every committed write ships the new
manifest+catalog (``sync``, called from the session's post-commit hook,
like WAL shipping to the standby master), and ``activate`` promotes the
copy to a servable cluster directory — pointed at the surviving segment
data trees, which mirrors (runtime/replication.py) protect separately.
A failing sync logs and never fails the write (async-standby semantics);
``gg state`` surfaces the lag."""

from __future__ import annotations

import json
import os
import tempfile

MARKER = "standby.json"
PRIMARY_MARKER = "standby_registered.json"

# manifest.json LAST: it is the commit record — if the sync dies midway,
# the standby's manifest must never be newer than the dictionaries it
# references (the WAL commit-point-last rule)
_META_FILES = ("calibration.json", "catalog.json", "manifest.json")


def _copy_file(src: str, dst: str) -> None:
    from greengage_tpu.storage.archive import _atomic_copy

    _atomic_copy(src, dst)


def _sync_meta(cluster_path: str, standby_path: str) -> None:
    # dictionaries first (append-only: re-copy only the ones that grew)
    data = os.path.join(cluster_path, "data")
    if os.path.isdir(data):
        for tdir in os.listdir(data):
            src_dir = os.path.join(data, tdir)
            if not os.path.isdir(src_dir):
                continue
            for fn in os.listdir(src_dir):
                if not fn.startswith("dict_"):
                    continue
                src = os.path.join(src_dir, fn)
                dst = os.path.join(standby_path, "data", tdir, fn)
                try:
                    if (not os.path.exists(dst)
                            or os.path.getsize(dst) != os.path.getsize(src)):
                        _copy_file(src, dst)
                except OSError:
                    pass
    for fn in _META_FILES:
        src = os.path.join(cluster_path, fn)
        if fn == "manifest.json":
            # ship the COMPOSED snapshot (root + committed per-table
            # deltas), not the raw root file: an activated standby opens a
            # plain root and must not lose delta commits folded only on
            # the primary (storage/manifest.py)
            _write_composed_manifest(cluster_path, standby_path)
        elif os.path.exists(src):
            _copy_file(src, os.path.join(standby_path, fn))


_MANIFESTS: dict = {}


def _composed_snapshot(cluster_path: str) -> dict:
    """Composed (root + committed deltas) snapshot for a cluster dir. The
    Manifest instance is reused across syncs so its file-signature memo
    serves the hot path — every post-commit standby sync would otherwise
    re-read the log plus one file per unfolded delta."""
    from greengage_tpu.storage.manifest import Manifest

    m = _MANIFESTS.get(cluster_path)
    if m is None:
        if len(_MANIFESTS) > 8:
            _MANIFESTS.clear()      # tests churn many tmp cluster dirs
        m = _MANIFESTS[cluster_path] = Manifest(cluster_path)
    return m.snapshot()


def _write_composed_manifest(cluster_path: str, standby_path: str) -> None:
    snap = _composed_snapshot(cluster_path)
    if not os.path.exists(os.path.join(cluster_path, "manifest.json")) \
            and not snap.get("version"):
        return

    fd, tmp = tempfile.mkstemp(dir=standby_path, prefix=".manifest")
    with os.fdopen(fd, "w") as f:
        json.dump(snap, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(standby_path, "manifest.json"))


def init_standby(cluster_path: str, standby_path: str) -> dict:
    """Seed the standby with the coordinator's current metadata and
    register it on the primary so every future commit syncs."""
    if os.path.abspath(standby_path) == os.path.abspath(cluster_path):
        raise ValueError("standby path must differ from the cluster path")
    os.makedirs(standby_path, exist_ok=True)
    _sync_meta(cluster_path, standby_path)
    version = _composed_snapshot(cluster_path).get("version", 0)
    marker = {"role": "standby", "primary": os.path.abspath(cluster_path),
              "synced_version": version}
    with open(os.path.join(standby_path, MARKER), "w") as f:
        json.dump(marker, f, indent=1)
    with open(os.path.join(cluster_path, PRIMARY_MARKER), "w") as f:
        json.dump({"standby_path": os.path.abspath(standby_path)}, f)
    return marker


def registered_standby(cluster_path: str) -> str | None:
    p = os.path.join(cluster_path, PRIMARY_MARKER)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f).get("standby_path")
    except (OSError, ValueError):
        return None


def sync(cluster_path: str, standby_path: str) -> int:
    """Ship the newest committed state; -> synced manifest version.

    Fenced two ways: the target must still carry its standby marker (a
    dead/unmounted standby directory must FAIL the sync loudly, not be
    silently resurrected as an empty local dir reporting itself synced),
    and a target whose marker says 'activated' is a PROMOTED coordinator
    — overwriting it would be split-brain data loss, exactly the state a
    partitioned old primary would create."""
    mp = os.path.join(standby_path, MARKER)
    try:
        with open(mp) as f:
            marker = json.load(f)
    except (OSError, ValueError) as e:
        raise RuntimeError(
            f"standby at {standby_path} has no readable marker "
            f"(gone/unmounted?): {e}")
    if marker.get("role") == "activated":
        raise RuntimeError(
            f"standby at {standby_path} was ACTIVATED; refusing to "
            "overwrite a promoted coordinator (split-brain fence) — "
            "remove this primary's standby registration")
    _sync_meta(cluster_path, standby_path)
    with open(os.path.join(standby_path, "manifest.json")) as f:
        version = json.load(f).get("version", 0)
    marker["synced_version"] = version
    with open(mp, "w") as f:
        json.dump(marker, f, indent=1)
    return version


def status(standby_path: str) -> dict:
    with open(os.path.join(standby_path, MARKER)) as f:
        return json.load(f)


def activate(standby_path: str, data_path: str | None = None) -> dict:
    """Promote the standby to a servable cluster directory
    (gpactivatestandby): the metadata copy becomes authoritative; segment
    data stays where it survived — ``data_path`` links the standby to it
    (mirror trees / shared storage). In-doubt manifests resolve on the
    first connect's recover()."""
    st = status(standby_path)
    if st.get("role") == "activated":
        return st
    data_dir = os.path.join(standby_path, "data")
    if not os.path.isdir(data_dir):
        if data_path is None:
            primary_data = os.path.join(st.get("primary", ""), "data")
            if os.path.isdir(primary_data):
                data_path = primary_data
            else:
                raise ValueError(
                    "standby has no data tree and the primary's is gone; "
                    "pass the surviving data directory via data_path")
        # dict files may already live under standby/data; a symlink would
        # shadow them — only link when nothing was synced there yet
        os.symlink(os.path.abspath(data_path), data_dir)
    elif data_path is not None:
        # merge: link each missing table dir into the synced data tree
        for tdir in os.listdir(data_path):
            src = os.path.join(data_path, tdir)
            dst = os.path.join(data_dir, tdir)
            if os.path.isdir(src) and not os.path.exists(dst):
                os.symlink(os.path.abspath(src), dst)
            elif os.path.isdir(src) and os.path.isdir(dst):
                for fn in os.listdir(src):
                    d2 = os.path.join(dst, fn)
                    if not os.path.exists(d2):
                        os.symlink(os.path.abspath(os.path.join(src, fn)), d2)
    st["role"] = "activated"
    with open(os.path.join(standby_path, MARKER), "w") as f:
        json.dump(st, f, indent=1)
    # the promoted coordinator must not keep syncing to itself
    try:
        os.remove(os.path.join(standby_path, PRIMARY_MARKER))
    except OSError:
        pass
    return st
