"""Master standby — the gpinitstandby / gpactivatestandby analog
(reference: gpMgmt/bin/gpinitstandby:1, gpactivatestandby:1), grown into
the automatic coordinator-failover plane (docs/ROBUSTNESS.md
"Coordinator failover").

The coordinator's durable state is small and file-shaped: catalog.json
(schemas/topology/stats), manifest.json + commits.log + deltas/ +
intents/ (the distributed commit record, storage/manifest.py),
append-only dictionary files, and calibration.json. A standby is a
directory holding a continuously-tailed RAW copy of exactly that state:
``init_standby`` seeds it, every committed write ships the tail from the
session's post-commit hook (``sync``, like WAL shipping to the standby
master), and the watcher daemon (``gg standby --watch``) pull-syncs on a
cadence and auto-promotes when the primary's liveness beat goes silent.

Ship order inside one sync (the WAL commit-point-last rule):
dictionaries -> commits.log tail -> delta files -> intent mirror ->
calibration/catalog -> the RAW root manifest.json LAST. The root is
shipped raw (NOT the composed snapshot): the root carries delta_seqs /
intent_seqs / log_pos, so root + shipped log + shipped delta files
compose on the standby to exactly the primary's committed state, and —
critically — the promoted standby's ``recover()`` sees honest in-doubt
evidence (staged-but-uncommitted claims and intent markers roll back
there exactly as they would on a restarted primary). Shipping a composed
root next to a raw log would double-apply every logged commit.

A failing sync logs, counts (``standby_sync_fail_total``), widens the
``standby_lag_commits`` gauge, and never fails the write (async-standby
semantics). Promotion is fence-first: the standby links an exclusive
``coordinator.fence`` claim into the PRIMARY cluster dir before touching
anything else, and every manifest commit point re-verifies it — a
paused-not-dead primary wakes to CoordinatorFenced, never split-brain.
"""

from __future__ import annotations

import fcntl
import json
import os
import tempfile
import threading
import time

from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters

MARKER = "standby.json"
PRIMARY_MARKER = "standby_registered.json"
# liveness beat the primary stamps (Database init, every post-commit,
# the FTS prober cadence); the standby watcher reads its age
BEAT = "coordinator.alive"
# the promotion fence: an exclusive hard-link claim the promoting
# standby places in the PRIMARY cluster dir (the atomic-token
# discipline storage/manifest.py uses for delta claims); the old
# primary re-verifies it inside every locked commit point
FENCE = "coordinator.fence"

# manifest.json LAST: it is the commit record — if the sync dies midway,
# the standby's root must never be newer than the log/deltas/dictionaries
# it references (the WAL commit-point-last rule)
_META_FILES = ("settings.json", "calibration.json", "feedback.json",
               "catalog.json", "manifest.json")


def _copy_file(src: str, dst: str) -> None:
    from greengage_tpu.storage.archive import _atomic_copy

    _atomic_copy(src, dst)


def _write_json(dir_path: str, final_path: str, obj: dict,
                fsync: bool = True) -> None:
    fd, tmp = tempfile.mkstemp(dir=dir_path, prefix=".standby")
    with os.fdopen(fd, "w") as f:
        json.dump(obj, f, indent=1)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, final_path)


# ---- raw tail shipping -------------------------------------------------

def _sync_dicts(cluster_path: str, standby_path: str) -> int:
    """Append-only dictionary files: re-copy only the ones that grew.
    Returns the number of files that FAILED to ship."""
    fails = 0
    data = os.path.join(cluster_path, "data")
    if not os.path.isdir(data):
        return 0
    for tdir in os.listdir(data):
        src_dir = os.path.join(data, tdir)
        if not os.path.isdir(src_dir):
            continue
        for fn in os.listdir(src_dir):
            if not fn.startswith("dict_"):
                continue
            src = os.path.join(src_dir, fn)
            dst = os.path.join(standby_path, "data", tdir, fn)
            try:
                if (not os.path.exists(dst)
                        or os.path.getsize(dst) != os.path.getsize(src)):
                    _copy_file(src, dst)
            except OSError:
                fails += 1
    return fails


def _sync_log_tail(cluster_path: str, standby_path: str,
                   marker: dict) -> None:
    """Ship the commits.log tail incrementally. ``marker['log_offset']``
    is the shipped-byte watermark; the primary's log only ever appends
    during a process lifetime (recover()'s compaction truncate runs at
    exclusive-open startup only), so a shrink means the primary
    restarted-and-compacted and the whole log is recopied. A tail read
    that catches a torn in-flight append is safe: the byte watermark
    advances exactly past what was shipped, so the remainder of the line
    arrives on the next sync and the standby's composed state simply
    lags one commit (torn tails end the committed prefix)."""
    src = os.path.join(cluster_path, "commits.log")
    dst = os.path.join(standby_path, "commits.log")
    try:
        src_size = os.path.getsize(src)
    except OSError:
        src_size = 0
    shipped = int(marker.get("log_offset", 0))
    try:
        dst_size = os.path.getsize(dst)
    except OSError:
        dst_size = 0
    if src_size < shipped or dst_size != shipped:
        # primary compacted (restart recovery) or the standby copy
        # diverged from the watermark: recopy from byte zero
        shipped = 0
        try:
            os.remove(dst)
        except OSError:
            pass
    if src_size > shipped:
        with open(src, "rb") as f:
            f.seek(shipped)
            tail = f.read(src_size - shipped)
        fd = os.open(dst, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, tail)
            os.fsync(fd)
        finally:
            os.close(fd)
        shipped += len(tail)
    marker["log_offset"] = shipped


def _sync_dir_mirror(src_dir: str, dst_dir: str, suffix: str) -> int:
    """Mirror a manifest side-directory (deltas/, intents/): copy files
    that are new or size-changed, remove files the primary no longer has
    (folded deltas GC'd, intents resolved or swept — mirroring the
    deletes keeps the standby's in-doubt evidence honest). Returns the
    number of files that FAILED to ship."""
    fails = 0
    try:
        src_names = {fn for fn in os.listdir(src_dir) if fn.endswith(suffix)}
    except OSError:
        src_names = set()
    os.makedirs(dst_dir, exist_ok=True)
    try:
        dst_names = {fn for fn in os.listdir(dst_dir) if fn.endswith(suffix)}
    except OSError:
        dst_names = set()
    for fn in src_names:
        src = os.path.join(src_dir, fn)
        dst = os.path.join(dst_dir, fn)
        try:
            if (fn not in dst_names
                    or os.path.getsize(dst) != os.path.getsize(src)):
                _copy_file(src, dst)
        except OSError:
            fails += 1
    for fn in dst_names - src_names:
        try:
            os.remove(os.path.join(dst_dir, fn))
        except OSError:
            pass
    return fails


def _sync_meta(cluster_path: str, standby_path: str, marker: dict) -> None:
    """One raw tail ship, commit-point (root) last. Per-file dictionary /
    delta / intent failures are counted and skipped (best-effort, the
    next sync retries); log-tail and root failures PROPAGATE — the
    caller counts them and the lag gauge grows."""
    fails = _sync_dicts(cluster_path, standby_path)
    _sync_log_tail(cluster_path, standby_path, marker)
    fails += _sync_dir_mirror(os.path.join(cluster_path, "deltas"),
                              os.path.join(standby_path, "deltas"),
                              ".delta")
    fails += _sync_dir_mirror(os.path.join(cluster_path, "intents"),
                              os.path.join(standby_path, "intents"),
                              ".intent")
    if fails:
        counters.inc("standby_sync_fail_total", fails)
    for fn in _META_FILES:
        src = os.path.join(cluster_path, fn)
        if os.path.exists(src):
            _copy_file(src, os.path.join(standby_path, fn))


_MANIFESTS: dict = {}
_MANIFESTS_LOCK = threading.Lock()


def _primary_manifest(cluster_path: str):
    """Memoized Manifest for a primary dir: its compose memo serves the
    per-commit version probe (every post-commit sync asks the effective
    version; re-opening would re-read the log each time). Locked: the
    watcher daemon, ingest flusher, and statement threads all probe."""
    from greengage_tpu.storage.manifest import Manifest

    with _MANIFESTS_LOCK:
        m = _MANIFESTS.get(cluster_path)
        if m is None:
            if len(_MANIFESTS) > 8:
                _MANIFESTS.clear()  # tests churn many tmp cluster dirs
            m = _MANIFESTS[cluster_path] = Manifest(cluster_path)
        return m


def _primary_version(cluster_path: str) -> int:
    return int(_primary_manifest(cluster_path).version())


def init_standby(cluster_path: str, standby_path: str) -> dict:
    """Seed the standby with the coordinator's current state and
    register it on the primary so every future commit ships the tail."""
    if os.path.abspath(standby_path) == os.path.abspath(cluster_path):
        raise ValueError("standby path must differ from the cluster path")
    os.makedirs(standby_path, exist_ok=True)
    marker = {"role": "standby", "primary": os.path.abspath(cluster_path),
              "synced_version": _primary_version(cluster_path)}
    _sync_meta(cluster_path, standby_path, marker)
    _write_json(standby_path, os.path.join(standby_path, MARKER), marker)
    with open(os.path.join(cluster_path, PRIMARY_MARKER), "w") as f:
        json.dump({"standby_path": os.path.abspath(standby_path)}, f)
    return marker


def registered_standby(cluster_path: str) -> str | None:
    p = os.path.join(cluster_path, PRIMARY_MARKER)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f).get("standby_path")
    except (OSError, ValueError):
        return None


def _sync_lock(standby_path: str) -> int:
    """Exclusive standby-side ship lock: the primary's push-sync, the
    watcher's pull-sync, and promotion all mutate the same standby files
    from different processes — the flock serializes whole ships so the
    byte-watermark tail append never interleaves with a recopy (and
    promotion's recover() never races a queued push). Raises OSError
    when the standby dir itself is gone: the loud-failure contract."""
    fd = os.open(os.path.join(standby_path, ".sync.lock"),
                 os.O_CREAT | os.O_RDWR, 0o644)
    fcntl.flock(fd, fcntl.LOCK_EX)
    return fd


def sync(cluster_path: str, standby_path: str) -> int:
    """Ship the newest committed tail; -> synced manifest version.

    Fenced two ways: the target must still carry its standby marker (a
    dead/unmounted standby directory must FAIL the sync loudly, not be
    silently resurrected as an empty local dir reporting itself synced),
    and a target whose marker says 'activated' is a PROMOTED coordinator
    — overwriting it would be split-brain data loss, exactly the state a
    partitioned old primary would create."""
    fd = _sync_lock(standby_path)
    try:
        return _sync_locked(cluster_path, standby_path)
    finally:
        os.close(fd)


def _sync_locked(cluster_path: str, standby_path: str) -> int:
    mp = os.path.join(standby_path, MARKER)
    try:
        with open(mp) as f:
            marker = json.load(f)
    except (OSError, ValueError) as e:
        raise RuntimeError(
            f"standby at {standby_path} has no readable marker "
            f"(gone/unmounted?): {e}")
    if marker.get("role") == "activated":
        raise RuntimeError(
            f"standby at {standby_path} was ACTIVATED; refusing to "
            "overwrite a promoted coordinator (split-brain fence) — "
            "remove this primary's standby registration")
    faults.check("standby_ship")
    # version BEFORE the ship: everything at/below it is covered by the
    # copies that follow, so the watermark is conservative under
    # concurrent commits
    version = _primary_version(cluster_path)
    _sync_meta(cluster_path, standby_path, marker)
    marker["synced_version"] = version
    _write_json(standby_path, mp, marker, fsync=False)
    counters.set("standby_lag_commits", 0)
    return version


def lag(cluster_path: str) -> int:
    """Committed-version distance between the primary and its registered
    standby's last successful ship (0 when none is registered)."""
    sb = registered_standby(cluster_path)
    if sb is None:
        return 0
    try:
        synced = int(status(sb).get("synced_version", 0))
    except (OSError, ValueError):
        synced = 0      # standby marker unreadable: the whole tail lags
    try:
        return max(0, _primary_version(cluster_path) - synced)
    except Exception:
        return 0


def note_sync_failure(cluster_path: str) -> None:
    """Account one failed ship: count it and refresh the lag gauge (the
    formerly-silent OSError swallow, now a first-class signal)."""
    counters.inc("standby_sync_fail_total")
    counters.set("standby_lag_commits", lag(cluster_path))


def status(standby_path: str) -> dict:
    with open(os.path.join(standby_path, MARKER)) as f:
        return json.load(f)


# ---- primary liveness beat ---------------------------------------------

def primary_beat(cluster_path: str, topology_version: int = 0) -> None:
    """Stamp the coordinator liveness beat the standby watcher reads.
    Best-effort: a missed stamp only ages the file, and the watcher
    tolerates staleness up to standby_promote_deadline_s."""
    try:
        fd, tmp = tempfile.mkstemp(dir=cluster_path, prefix=".beat")
        with os.fdopen(fd, "w") as f:
            json.dump({"pid": os.getpid(), "ts": time.time(),
                       "topology_version": int(topology_version)}, f)
        os.replace(tmp, os.path.join(cluster_path, BEAT))
    except OSError:
        pass


def beat_age(cluster_path: str) -> float:
    """Seconds since the primary last stamped its beat (inf = never)."""
    try:
        with open(os.path.join(cluster_path, BEAT)) as f:
            ts = float(json.load(f).get("ts", 0.0))
    except (OSError, ValueError):
        return float("inf")
    return max(0.0, time.time() - ts)


# ---- the promotion fence -----------------------------------------------

def write_fence(cluster_path: str, standby_path: str,
                reason: str = "promotion") -> dict:
    """Place the exclusive promotion claim in the PRIMARY cluster dir.
    The hard link is the CAS (two racing standbys cannot both fence);
    re-fencing by the same standby is idempotent. Every manifest commit
    point re-verifies this file, so a paused-not-dead primary's next
    commit raises CoordinatorFenced instead of forking the lineage."""
    data = {"standby": os.path.abspath(standby_path), "reason": reason,
            "ts": time.time(), "pid": os.getpid()}
    path = os.path.join(cluster_path, FENCE)
    fd, tmp = tempfile.mkstemp(dir=cluster_path, prefix=".fence")
    with os.fdopen(fd, "w") as f:
        json.dump(data, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
    except FileExistsError:
        os.remove(tmp)
        cur = fenced(cluster_path) or {}
        if cur.get("standby") == data["standby"]:
            return cur
        raise RuntimeError(
            f"cluster at {cluster_path} is already fenced by "
            f"{cur.get('standby')!r} — two standbys raced; this one "
            "must NOT promote")
    os.remove(tmp)
    return data


def fenced(cluster_path: str) -> dict | None:
    """The fence claim if this cluster dir has been fenced, else None."""
    try:
        with open(os.path.join(cluster_path, FENCE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_fence(cluster_path: str) -> None:
    """Operator escape hatch (`gg standby --unfence` after re-initing a
    demoted primary as the new standby)."""
    try:
        os.remove(os.path.join(cluster_path, FENCE))
    except OSError:
        pass


# ---- activation & promotion --------------------------------------------

def activate(standby_path: str, data_path: str | None = None) -> dict:
    """Promote the standby to a servable cluster directory
    (gpactivatestandby): the metadata copy becomes authoritative; segment
    data stays where it survived — ``data_path`` links the standby to it
    (mirror trees / shared storage). In-doubt manifests resolve on the
    first connect's recover()."""
    st = status(standby_path)
    if st.get("role") == "activated":
        return st
    data_dir = os.path.join(standby_path, "data")
    if not os.path.isdir(data_dir):
        if data_path is None:
            primary_data = os.path.join(st.get("primary", ""), "data")
            if os.path.isdir(primary_data):
                data_path = primary_data
            else:
                raise ValueError(
                    "standby has no data tree and the primary's is gone; "
                    "pass the surviving data directory via data_path")
        # dict files may already live under standby/data; a symlink would
        # shadow them — only link when nothing was synced there yet
        os.symlink(os.path.abspath(data_path), data_dir)
    elif data_path is not None:
        # merge: link each missing table dir into the synced data tree
        for tdir in os.listdir(data_path):
            src = os.path.join(data_path, tdir)
            dst = os.path.join(data_dir, tdir)
            if os.path.isdir(src) and not os.path.exists(dst):
                os.symlink(os.path.abspath(src), dst)
            elif os.path.isdir(src) and os.path.isdir(dst):
                for fn in os.listdir(src):
                    d2 = os.path.join(dst, fn)
                    if not os.path.exists(d2):
                        os.symlink(os.path.abspath(os.path.join(src, fn)), d2)
    st["role"] = "activated"
    _write_json(standby_path, os.path.join(standby_path, MARKER), st)
    # the promoted coordinator must not keep syncing to itself
    try:
        os.remove(os.path.join(standby_path, PRIMARY_MARKER))
    except OSError:
        pass
    return st


def _bump_topology_version(standby_path: str) -> int:
    """Advance the promoted catalog's segment-config version so every
    cached dispatch topology (workers included) re-reads the cluster
    state — the FTS-version bump the reference performs on promotion."""
    cat = os.path.join(standby_path, "catalog.json")
    try:
        with open(cat) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return 0
    seg = data.get("segments")
    if not isinstance(seg, dict):
        return 0
    seg["version"] = int(seg.get("version", 0)) + 1
    _write_json(standby_path, cat, data)
    return seg["version"]


def promote(standby_path: str, data_path: str | None = None,
            reason: str = "primary-silent") -> dict:
    """The automatic-failover promotion state machine: fence -> final
    tail pull -> activate -> recover -> topology bump. Idempotent once
    activated. Fence FIRST: from that point the old primary's next
    commit raises CoordinatorFenced, so the pull that follows ships the
    FINAL committed tail (cluster files outlive the dead process) and
    nothing can land behind the promotion's back. ``recover()`` then
    resolves the in-doubt evidence honestly — staged delta claims and
    unresolved write-intents roll back, durable merge lines survive —
    exactly the startup contract a restarted primary gets."""
    lock_fd = _sync_lock(standby_path)
    try:
        st = status(standby_path)
        if st.get("role") == "activated":
            return st
        faults.check("standby_promote")
        primary = st.get("primary", "")
        if primary and os.path.isdir(primary):
            write_fence(primary, standby_path, reason)
            try:
                _sync_locked(primary, standby_path)
            except Exception:
                # the last-shipped state is still a consistent commit
                # prefix (root-last ordering); promote from it rather
                # than refuse
                counters.inc("standby_sync_fail_total")
            # the common failover shape: the coordinator PROCESS died,
            # the segment data trees survived — adopt them by default
            if data_path is None:
                pd = os.path.join(primary, "data")
                if os.path.isdir(pd):
                    data_path = pd
        st = activate(standby_path, data_path)
        from greengage_tpu.storage.manifest import Manifest

        Manifest(standby_path).recover()
        topo = _bump_topology_version(standby_path)
        counters.inc("standby_promote_total")
        counters.set("standby_lag_commits", 0)
        st["promoted"] = {"reason": reason, "ts": time.time(),
                          "topology_version": topo}
        _write_json(standby_path, os.path.join(standby_path, MARKER), st)
        return st
    finally:
        os.close(lock_fd)


# ---- the watcher daemon (`gg standby --watch`) -------------------------

class StandbyWatcher:
    """Standby-side failover daemon, the FtsProber of the coordinator
    itself: each poll pull-syncs the primary's commit tail (push from
    the post-commit hook + this pull keeps lag bounded even when the
    primary's push path fails) and reads the liveness beat; once the
    primary has been silent past ``deadline_s`` it runs ``promote()``.
    A beat-less primary (older build, beat file unlinked) gets one full
    deadline window measured from watcher start before it counts as
    silent."""

    def __init__(self, standby_path: str, interval_s: float = 1.0,
                 deadline_s: float = 15.0, data_path: str | None = None,
                 on_promote=None):
        self.standby_path = standby_path
        self.interval_s = max(0.01, float(interval_s))
        self.deadline_s = float(deadline_s)
        self.data_path = data_path
        self.on_promote = on_promote
        self.promoted: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started = 0.0

    def poll_once(self) -> bool:
        """One watch step; -> True once the standby is promoted."""
        if not self._started:
            self._started = time.time()
        st = status(self.standby_path)
        if st.get("role") == "activated":
            self.promoted = st
            return True
        primary = st.get("primary", "")
        try:
            sync(primary, self.standby_path)
        except Exception:
            note_sync_failure(primary)
        silent = min(beat_age(primary), time.time() - self._started)
        if silent >= self.deadline_s:
            self.promoted = promote(
                self.standby_path, self.data_path,
                reason=f"primary silent {silent:.1f}s "
                       f"(deadline {self.deadline_s:.1f}s)")
            if self.on_promote is not None:
                self.on_promote(self.promoted)
            return True
        return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._started = time.time()

        def loop() -> None:
            from greengage_tpu.runtime.retry import backoff_delays

            delays = None
            while not self._stop.is_set():
                try:
                    if self.poll_once():
                        return
                    delays = None
                    wait = self.interval_s
                except Exception:
                    # transient watch errors (primary dir flapping) back
                    # off instead of spinning; the next good poll resets
                    if delays is None:
                        delays = backoff_delays(base=self.interval_s,
                                                cap=self.interval_s * 8,
                                                jitter=0.25)
                    wait = next(delays)
                if self._stop.wait(wait):
                    return

        self._thread = threading.Thread(target=loop, name="gg-standby-watch",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
