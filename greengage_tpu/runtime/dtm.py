"""DTM-lite: session transactions over the manifest's two-phase commit.

Reference parity: the distributed transaction manager (src/backend/cdb/
cdbtm.c — doPrepareTransaction:418, doNotifyingCommitPrepared:566) with the
manifest version swap as the distributed commit record (see
storage/manifest.py). A transaction batches any number of writes; commit
runs prepare (durably stage) -> flush dictionaries -> atomic swap, with
fault points at each phase so tests can kill the coordinator mid-2PC and
assert recovery (crash_recovery_dtm.sql analog). One-phase optimization:
a read-only transaction commits without touching the manifest.
"""

from __future__ import annotations

from greengage_tpu.runtime.faultinject import faults


class TransactionError(RuntimeError):
    pass


class Transaction:
    def __init__(self, store):
        self.store = store
        self.tx = store.manifest.begin()
        self.tables_written: set[str] = set()
        self.state = "active"     # active | prepared | committed | aborted

    def insert(self, table: str, columns, valids=None) -> int:
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        n = self.store.insert(table, columns, valids, tx=self.tx)
        self.tables_written.add(table)
        return n

    def commit(self) -> None:
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        if not self.tables_written:     # one-phase: nothing to publish
            self.state = "committed"
            return
        faults.check("dtx_before_prepare")
        try:
            version = self.store.manifest.prepare(self.tx)
        except RuntimeError as e:
            self.state = "aborted"
            raise TransactionError(str(e))
        self.state = "prepared"
        faults.check("dtx_after_prepare")       # crash here -> recover() rolls back
        for t in self.tables_written:
            self.store.flush_dicts(t)
        faults.check("dtx_before_commit")
        self.store.manifest.commit(version)
        self.state = "committed"

    def abort(self) -> None:
        if self.state in ("committed",):
            raise TransactionError("already committed")
        self.state = "aborted"
        for t in self.tables_written:
            self.store._invalidate_dicts(t)


class DtmSession:
    """Per-Database transaction bookkeeping (MyTmGxact analog)."""

    def __init__(self, store):
        self.store = store
        self.current: Transaction | None = None

    def begin(self) -> Transaction:
        if self.current is not None and self.current.state == "active":
            raise TransactionError("transaction already in progress")
        self.current = Transaction(self.store)
        return self.current

    def commit(self) -> None:
        if self.current is None or self.current.state != "active":
            raise TransactionError("no transaction in progress")
        self.current.commit()
        self.current = None

    def abort(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        self.current.abort()
        self.current = None
