"""DTM-lite: session transactions over the manifest's two-phase commit.

Reference parity: the distributed transaction manager (src/backend/cdb/
cdbtm.c — doPrepareTransaction:418, doNotifyingCommitPrepared:566) with the
manifest version swap as the distributed commit record (see
storage/manifest.py). A transaction batches any number of writes; commit
runs prepare (durably stage) -> flush dictionaries -> atomic swap, with
fault points at each phase so tests can kill the coordinator mid-2PC and
assert recovery (crash_recovery_dtm.sql analog). One-phase optimization:
a read-only transaction commits without touching the manifest.
"""

from __future__ import annotations

from greengage_tpu.runtime.faultinject import faults


class TransactionError(RuntimeError):
    pass


class Transaction:
    def __init__(self, store):
        self.store = store
        self.tx = store.manifest.begin()
        self.tables_written: set[str] = set()
        self._gc: list = []       # (table, old rels) GC'd after commit
        self.state = "active"     # active | prepared | committed | aborted

    def insert(self, table: str, columns, valids=None) -> int:
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        n = self.store.insert(table, columns, valids, tx=self.tx)
        self.tables_written.add(table)
        return n

    def insert_encoded(self, table: str, enc, valids, raw_strs=None) -> int:
        """Stage an already-encoded append (the UPDATE new-row half of the
        visimap split: delete bitmap + appended row versions)."""
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        n = self.store.insert_encoded(table, enc, valids, raw_strs,
                                      tx=self.tx)
        self.tables_written.add(table)
        return n

    def set_delmask(self, table: str, masks) -> None:
        """Stage deletion bitmaps; replaced bitmaps are GC'd at commit,
        the new ones reclaimed on rollback."""
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        old = self.store.stage_delmask(self.tx, table, masks)
        new_rels = [self.tx["tables"][table]["delmask"][str(s)]
                    for s in masks]
        if not hasattr(self, "_staged_new"):
            self._staged_new = []
        self._staged_new.append((table, new_rels))
        self._gc.append((table, old))
        self.tables_written.add(table)

    def replace(self, table: str, enc, valids, raw_strs=None) -> None:
        """Stage a DELETE/UPDATE republish; the old files become
        unreachable at commit and are GC'd then, the NEW files are
        reclaimed if the transaction rolls back."""
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        old = self.store.stage_replace(self.tx, table, enc, valids, raw_strs)
        new_rels = [rel for files in self.tx["tables"][table]["segfiles"].values()
                    for rel in files]
        if not hasattr(self, "_staged_new"):
            self._staged_new = []
        self._staged_new.append((table, new_rels))
        self._gc.append((table, old))
        self.tables_written.add(table)

    def commit(self) -> None:
        """Two-phase commit over the PER-TABLE delta path: stage one delta
        per written table (each claimed on its table's own sequence — the
        per-table CAS, so transactions touching different tables never
        conflict), then append the single fsynced commit-log line that
        makes every table's delta visible atomically. Fault points bracket
        the same phases the reference's crash_recovery_dtm kills at."""
        if self.state != "active":
            raise TransactionError(f"transaction is {self.state}")
        if not self.tables_written:     # one-phase: nothing to publish
            self.state = "committed"
            return
        faults.check("dtx_before_prepare")
        try:
            handle = self.store.manifest.prepare_delta(
                self.tx, sorted(self.tables_written))
        except RuntimeError as e:
            self.abort()
            raise TransactionError(str(e))
        self.state = "prepared"
        self._prepared_handle = handle
        faults.check("dtx_after_prepare")       # crash here -> recover() rolls back
        try:
            for t in self.tables_written:
                self.store.flush_dicts(t)
            faults.check("dtx_before_commit")
            # a reform racing the commit path (tests park a committer here
            # while the mesh re-forms: the manifest is coordinator-local,
            # so the commit must complete regardless of gang state)
            faults.check("commit_during_reform")
            self.store.manifest.commit_delta(handle)
        except BaseException:
            # release the per-table claims: stale claims would block every
            # same-table writer until recover() (r2 review finding)
            self.store.manifest.abort_delta(handle)
            self.state = "aborted"
            raise
        self.state = "committed"
        faults.check("dtx_after_commit")   # crash here -> commit survives
        for table, rels in self._gc:
            self.store.gc_files(table, rels)
        self.store.maybe_fold_manifest()

    def abort(self) -> None:
        if self.state in ("committed",):
            raise TransactionError("already committed")
        if self.state == "prepared" and getattr(self, "_prepared_handle", None):
            self.store.manifest.abort_delta(self._prepared_handle)
        self.state = "aborted"
        for t in self.tables_written:
            self.store._invalidate_dicts(t)
        # the replacement files staged by in-tx DML are manifest-unreachable
        # now; reclaim them instead of leaking a table copy per rollback
        for table, new_rels in getattr(self, "_staged_new", []):
            self.store.gc_files(table, new_rels, defer=False)


class DtmSession:
    """Per-Database transaction bookkeeping (MyTmGxact analog)."""

    def __init__(self, store):
        self.store = store
        self.current: Transaction | None = None

    def begin(self) -> Transaction:
        if self.current is not None and self.current.state == "active":
            raise TransactionError("transaction already in progress")
        self.current = Transaction(self.store)
        return self.current

    def commit(self) -> None:
        if self.current is None or self.current.state != "active":
            raise TransactionError("no transaction in progress")
        self.current.commit()
        self.current = None

    def abort(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        self.current.abort()
        self.current = None
