"""Line-protocol SQL server — the postmaster/libpq listener analog.

A thin concurrent front end over one Database: clients connect to a unix
socket (or TCP port) and exchange newline-delimited JSON:

    -> {"sql": "select ..."}
    <- {"ok": true, "columns": [...], "rows": [[...], ...], "tag": null}
    <- {"ok": false, "error": "..."}

Control frames ride the same protocol (the pg_stat_activity /
pg_cancel_backend surface, served from ANOTHER connection since the
executing one is blocked in its statement):

    -> {"op": "ps"}            <- {"ok": true, "rows": [activity...]}
    -> {"op": "cancel", "id": N}  <- {"ok": true/false}
    -> {"op": "mem"}           <- {"ok": true, "mem": {device/accounts...}}

Reference parity: exec_simple_query serving many clients
(src/backend/tcop/postgres.c:1622). Each connection gets a thread; SELECTs
run lock-free on manifest snapshots, write statements serialize on the
session write lock (one writer gang at a time), so concurrent COPY +
SELECT + UPDATE interleave safely. Transaction state is per connection
(the Database keeps one DtmSession per thread, and each connection is a
thread), so BEGIN/COMMIT/ROLLBACK work over the wire; a connection that
drops mid-transaction is rolled back, like a backend exiting — and a
disconnect observed mid-exchange cancels the connection's in-flight
statement with cause ``client_gone`` instead of letting the broken-pipe
error escape into socketserver. Conflicting commits fail at the manifest
CAS with a serialization error.

Overload armor (docs/ROBUSTNESS.md "Overload protection") — the front
end is BOUNDED in every dimension a hostile or overloaded client could
grow it:

  * ``max_connections`` caps concurrent handler threads; an excess
    connect receives one typed, retryable ``too_many_connections`` frame
    (the SQLSTATE 53300 fast-fail) and the socket closes — never silent
    thread growth. ``connections_shed_total`` counts the sheds and the
    ``server_active_connections`` gauge tracks the live population; the
    ``overload_accept`` fault point forces the shed path in tests.
  * ``client_auth_deadline_s`` bounds the TCP auth handshake and
    ``client_idle_timeout_s`` (optional) bounds idle reads between
    statements, so a wedged peer cannot pin a handler forever.
  * ``max_frame_bytes`` bounds one request frame; an oversized line gets
    a typed ``frame_too_large`` error and the connection closes (the
    stream cannot be resynced past a partially-read line), so a
    multi-GB JSON line cannot OOM the host.
  * load-shed errors from admission (``AdmissionShed``,
    runtime/resqueue.py) map to a typed retryable frame with
    ``"sqlstate": "53300"``.
  * ``stop()`` drains gracefully: stop accepting, flag in-flight
    statements with cause ``shutdown`` via the interrupt registry,
    bounded join (``server_drain_s``), then force-close stragglers.

Disconnect watching is one ``_ConnWatcher`` thread PER CONNECTION (not
per statement): the handler arms it around each db.sql() and it parks
between statements, so a client pipelining 10k statements reuses one
watcher instead of spawning 10k short-lived threads.
"""

from __future__ import annotations

import errno
import json
import os
import socket
import socketserver
import threading
import time
import select

from greengage_tpu.runtime import lockdebug
from greengage_tpu.runtime import overload as _overload
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.interrupt import REGISTRY, StatementCancelled
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.resqueue import AdmissionShed

# select/recv errnos that genuinely prove the peer (or our own fd) is
# gone; anything else is a TRANSIENT poll hiccup that must NOT cancel a
# live client's statement (the old behavior treated every OSError as an
# EOF and killed healthy statements on a spurious select failure)
_WATCH_FATAL_ERRNOS = frozenset({
    errno.EBADF, errno.ENOTCONN, errno.ECONNRESET, errno.EPIPE,
    errno.ESHUTDOWN, errno.ECONNABORTED,
})

# consecutive transient poll failures before the watcher gives up on the
# CURRENT statement (without cancelling — losing disconnect detection is
# the lesser harm vs cancelling a live client's work)
_WATCH_TRANSIENT_LIMIT = 5


def _is_failover_error(e: BaseException) -> bool:
    """Coordinator-failover causes deserving the typed retryable 57P01
    frame: a stale fenced coordinator's refused commit, or the gang's
    coordinator channel dying out from under a dispatched statement.
    One causal hop is checked too — the session wraps commit errors."""
    from greengage_tpu.parallel.multihost import CoordinatorLost
    from greengage_tpu.storage.manifest import CoordinatorFenced

    kinds = (CoordinatorFenced, CoordinatorLost)
    if isinstance(e, kinds):
        return True
    return isinstance(e.__cause__ or e.__context__, kinds)


def _watch_tick(sock) -> str:
    """One disconnect-watch poll of the client socket. Returns:
    ``eof``   — the peer closed (or our fd is gone): the statement has
                nobody to read it;
    ``data``  — a pipelined request is buffered (client alive; the byte
                is PEEKed, never consumed);
    ``idle``  — nothing readable;
    ``transient`` — the poll itself failed for a reason that does not
                prove the peer is gone (spurious select error)."""
    try:
        r, _, _ = select.select([sock], [], [], 0)
        if not r:
            return "idle"
        if sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b"":
            return "eof"
        return "data"
    except (BlockingIOError, InterruptedError):
        return "idle"
    except ValueError:
        return "eof"       # fd already closed on our side (drain/teardown)
    except OSError as e:
        if e.errno in _WATCH_FATAL_ERRNOS:
            return "eof"
        return "transient"


class _ConnWatcher:
    """One client-disconnect watcher per CONNECTION: while the handler
    thread is blocked inside db.sql(), only a peeker can observe the
    client's EOF and flag the statement ``client_gone`` so it dies at
    its next cancellation point instead of running to completion for
    nobody. The handler arms the watcher around each statement; between
    statements (and after observing pipelined DATA, which means the
    client is alive) it parks on its condition instead of exiting, so
    one thread serves the whole connection's statement stream."""

    POLL_S = 0.1

    def __init__(self, sock, thread_ident: int):
        self._sock = sock
        self._ident = thread_ident
        self._mu = lockdebug.named(threading.Lock(), "server.watcher._mu")
        self._cv = threading.Condition(self._mu)
        self._armed = False
        self._stopping = False
        # arm/disarm epoch: a self-disarm (pipelined data / transient
        # streak) must not erase an arm() the handler issued for the
        # NEXT statement in the meantime
        self._gen = 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="gg-client-watch")
        self._thread.start()

    def arm(self) -> None:
        with self._cv:
            self._gen += 1
            self._armed = True
            self._cv.notify_all()

    def disarm(self) -> None:
        with self._cv:
            self._gen += 1
            self._armed = False

    def shutdown(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=1.0)

    def _loop(self) -> None:
        transient = 0
        while True:
            with self._cv:
                while not self._armed and not self._stopping:
                    self._cv.wait(0.5)
                if self._stopping:
                    return
                gen = self._gen
            state = _watch_tick(self._sock)
            if state == "eof":
                REGISTRY.cancel_thread(self._ident, "client_gone")
                return
            if state == "data":
                # buffered pipelined request: client alive — stop
                # watching THIS statement (never consume the byte)
                transient = 0
                self._self_disarm(gen)
                continue
            if state == "transient":
                transient += 1
                if transient >= _WATCH_TRANSIENT_LIMIT:
                    # a persistent poll failure proves nothing about the
                    # peer: give up on this statement WITHOUT cancelling
                    transient = 0
                    self._self_disarm(gen)
                    continue
            else:
                transient = 0
            time.sleep(self.POLL_S)

    def _self_disarm(self, gen: int) -> None:
        with self._cv:
            if self._gen == gen:   # handler has not re-armed since
                self._armed = False


def _pipeline_depths(db) -> dict:
    """Serving-pipeline queue depths for the ps/status frames: members
    waiting in batched-serving admission windows, batches staged-but-not-
    demuxed, and the staging pool's read-unit backlog (the PR-10
    staging_pool_queue_depth probe, reused rather than re-measured)."""
    from greengage_tpu.exec import staging

    out = {"staging_pool_queue_depth": staging.pool_queue_depth()}
    bs = getattr(db, "_batch_server", None)
    if bs is not None:
        try:
            out.update(bs.queue_depths())
        except Exception:
            pass
    return out


def _cluster_status(db) -> dict:
    """Topology state for the ps/status control frames; resilient to a
    Database predating mh_state (bare test doubles)."""
    try:
        return db.mh_state()
    except Exception:
        return {"state": "unknown", "topology_version": None}


def _encode_value(v):
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return str(v)


class SqlServer:
    def __init__(self, db, socket_path: str, host: str | None = None,
                 port: int | None = None):
        self.db = db
        self.socket_path = socket_path
        self.host, self.port = host, port
        self._server = None
        self._tcp_server = None
        self._thread = None
        self._tcp_thread = None
        # connection admission/drain state, shared with every handler
        # thread (declared in analysis/threadmodel.py SHARED_CLASSES;
        # all mutation under _conn_mu)
        self._conn_mu = lockdebug.named(threading.Lock(),
                                        "server._conn_mu")
        self._active_conns = 0
        self._served = 0
        self._draining = False
        self._conns: dict = {}      # thread ident -> client socket
        self._handlers: dict = {}   # thread ident -> handler Thread

    @property
    def connections_served(self) -> int:
        with self._conn_mu:
            return self._served

    # ---- bounded front end (admission / drain) -----------------------
    def _admit_connection(self, sock) -> tuple | None:
        """Admit the calling handler thread, or return the typed shed
        ``(code, message)``. The cap check and the bookkeeping are one
        atomic step under _conn_mu — two racing connects cannot both
        claim the last slot (the connections_served data race this
        replaces was exactly that shape)."""
        limit = int(getattr(self.db.settings, "max_connections", 0))
        try:
            forced = faults.check("overload_accept")
        except FaultError:
            forced = True
        me = threading.current_thread()
        with self._conn_mu:
            if self._draining:
                shed = ("shutting_down", "server is shutting down")
            elif forced or (limit > 0 and self._active_conns >= limit):
                shed = ("too_many_connections",
                        f"too many connections (max_connections={limit}, "
                        f"active={self._active_conns})")
            else:
                shed = None
                self._active_conns += 1
                self._served += 1
                self._conns[me.ident] = sock
                self._handlers[me.ident] = me
                # gauge set INSIDE the lock: a set outside with a
                # captured count can land out of order against a racing
                # release and leave the gauge wrong forever
                counters.set("server_active_connections",
                             self._active_conns)
        if shed is not None:
            counters.inc("connections_shed_total")
            self.db.log.log("WARNING", "overload",
                            f"connection shed: {shed[1]}")
            return shed
        counters.inc("server_connections_total")
        return None

    def _release_connection(self) -> None:
        me = threading.get_ident()
        with self._conn_mu:
            if self._conns.pop(me, None) is not None:
                self._active_conns -= 1
            self._handlers.pop(me, None)
            counters.set("server_active_connections",
                         self._active_conns)   # under the lock: ordered

    def _draining_now(self) -> bool:
        with self._conn_mu:
            return self._draining

    # ------------------------------------------------------------------
    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            REMOTE = False   # TCP subclass flips this: remote => auth

            def handle(self):
                shed = outer._admit_connection(self.connection)
                if shed is not None:
                    # typed fast-fail (SQLSTATE 53300 analog): one frame,
                    # then the socket closes — the client can back off
                    # and retry instead of hanging on a dead connection
                    self._send({"ok": False, "error": shed[1],
                                "code": shed[0], "sqlstate": "53300",
                                "retryable": True})
                    return
                try:
                    if self.REMOTE and not self._authenticate():
                        return
                    self._serve()
                finally:
                    outer._release_connection()
                    # a connection dropping mid-transaction rolls back, and
                    # its cursors close, like a libpq backend exiting
                    outer.db.abort_if_active()
                    outer.db.close_thread_cursors()

            def _send(self, obj: dict) -> None:
                """Best-effort frame write: a peer that vanished before
                reading its typed error is not an event worth a
                traceback."""
                try:
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()
                except (OSError, ValueError):
                    pass

            def _authenticate(self) -> bool:
                """Challenge-response over TCP (auth.c role): unix-socket
                peers are trusted, remote peers must prove a gg_hba.json
                password without sending it (runtime/auth.py). The whole
                handshake is bounded by client_auth_deadline_s — a peer
                that connects and goes silent cannot pin this handler."""
                from greengage_tpu.runtime import auth

                deadline = float(getattr(outer.db.settings,
                                         "client_auth_deadline_s", 10.0))
                old_timeout = self.connection.gettimeout()
                if deadline > 0:
                    self.connection.settimeout(deadline)
                users = auth.load_users(outer.db.path)
                ok = False
                timed_out = False
                try:
                    hello = json.loads(self.rfile.readline() or b"{}")
                    user = str(hello.get("user", ""))
                    ch = auth.challenge(users, user, outer.db.path)
                    self.wfile.write((json.dumps(ch) + "\n").encode())
                    self.wfile.flush()
                    resp = json.loads(self.rfile.readline() or b"{}")
                    ok = auth.verify(users, user, ch["nonce"],
                                     str(resp.get("proof", "")))
                    self.wfile.write((json.dumps(
                        {"ok": ok, "error": None if ok
                         else "authentication failed"}) + "\n").encode())
                    self.wfile.flush()
                except (socket.timeout, TimeoutError):
                    # silent peer past the deadline: shed the handler
                    ok = False
                    timed_out = True
                except Exception:
                    # dropped peers and malformed handshakes must not
                    # traceback per port-scan probe
                    ok = False
                finally:
                    try:
                        self.connection.settimeout(old_timeout)
                    except OSError:
                        pass
                if timed_out:
                    counters.inc("connections_shed_total")
                    outer.db.log.log(
                        "WARNING", "overload",
                        f"auth handshake exceeded client_auth_deadline_s"
                        f"={deadline:g}; connection closed")
                elif not ok:
                    outer.db.log.log("WARNING", "auth",
                                     "remote authentication failed")
                return ok

            def _serve(self):
                me = threading.get_ident()
                watcher = None
                settings = outer.db.settings
                idle_s = float(getattr(settings,
                                       "client_idle_timeout_s", 0.0))
                max_frame = int(getattr(settings,
                                        "max_frame_bytes", 64 << 20))
                if idle_s > 0:
                    try:
                        self.connection.settimeout(idle_s)
                    except OSError:
                        return
                try:
                    while True:
                        try:
                            line = self.rfile.readline(max_frame + 1)
                        except (socket.timeout, TimeoutError):
                            # idle past the deadline: typed goodbye
                            self._send({
                                "ok": False, "code": "idle_timeout",
                                "error": "connection idle beyond client_"
                                         f"idle_timeout_s={idle_s:g}; "
                                         "closing"})
                            return
                        if not line:
                            return      # EOF: client closed cleanly
                        if len(line) > max_frame:
                            # the stream cannot be resynced past a
                            # partially-read oversized line: reject AND
                            # close, so a multi-GB frame costs the host
                            # max_frame_bytes, not its full length
                            counters.inc("frames_rejected_total")
                            self._send({
                                "ok": False, "code": "frame_too_large",
                                "error": "request frame exceeds "
                                         f"max_frame_bytes={max_frame}; "
                                         "closing connection"})
                            return
                        line = line.strip()
                        if not line:
                            continue
                        if outer._draining_now():
                            self._send({
                                "ok": False, "code": "shutting_down",
                                "sqlstate": "53300", "retryable": True,
                                "error": "server is shutting down"})
                            return
                        try:
                            req = json.loads(line)
                            if "op" in req and "sql" not in req:
                                resp = self._control(req)
                            else:
                                # watch for a mid-statement disconnect:
                                # this thread is blocked in db.sql(), so
                                # only a peeker can observe the EOF and
                                # flag the statement client_gone. ONE
                                # watcher per connection, armed per
                                # statement (satellite: no thread per
                                # pipelined statement)
                                if watcher is None:
                                    watcher = _ConnWatcher(
                                        self.connection, me)
                                watcher.arm()
                                try:
                                    out = outer.db.sql(req["sql"])
                                finally:
                                    watcher.disarm()
                                if isinstance(out, str) or out is None:
                                    resp = {"ok": True, "columns": None,
                                            "rows": None, "tag": out}
                                else:
                                    resp = {
                                        "ok": True,
                                        "columns": list(out.columns),
                                        "rows": [[_encode_value(v)
                                                  for v in row]
                                                 for row in out.rows()],
                                        "tag": None,
                                    }
                        except StatementCancelled as e:
                            # surface the typed cause to the client (the
                            # '57014 query_canceled' SQLSTATE analog)
                            resp = {"ok": False, "error": f"{e}",
                                    "cancelled": e.cause}
                        except AdmissionShed as e:
                            # load shed (docs/ROBUSTNESS.md "Overload
                            # protection"): typed + retryable, the
                            # SQLSTATE 53300 queue-rejection analog
                            resp = {"ok": False, "error": f"{e}",
                                    "code": "admission_shed",
                                    "sqlstate": "53300",
                                    "retryable": True}
                        except Exception as e:  # per-statement isolation
                            if _is_failover_error(e):
                                # coordinator failover (docs/ROBUSTNESS.md
                                # "Coordinator failover"): the statement
                                # died because this coordinator was fenced
                                # by a promoted standby or lost its gang
                                # mid-failover — typed + retryable, the
                                # SQLSTATE 57P01 admin-shutdown analog;
                                # the client retries against the promoted
                                # coordinator's address
                                resp = {"ok": False, "error": f"{e}",
                                        "code": "coordinator_failover",
                                        "sqlstate": "57P01",
                                        "retryable": True}
                            else:
                                resp = {"ok": False, "error": f"{e}"}
                        try:
                            self.wfile.write(
                                (json.dumps(resp) + "\n").encode())
                            self.wfile.flush()
                        except (socket.timeout, TimeoutError):
                            # client_idle_timeout_s also deadlines WRITES
                            # (settimeout covers both directions): a
                            # reader too slow to drain its result within
                            # the idle budget is the same overload class
                            # as a silent peer — close, never traceback
                            outer.db.log.log(
                                "WARNING", "overload",
                                "response write exceeded client_idle_"
                                "timeout_s; closing connection")
                            return
                except (BrokenPipeError, ConnectionResetError):
                    # the client vanished mid-exchange: flag whatever this
                    # connection still has in flight as client_gone and
                    # end the handler cleanly — a disconnect must never
                    # traceback into socketserver (the statement dies at
                    # its next cancellation point and nobody reads the
                    # error)
                    REGISTRY.cancel_thread(me, "client_gone")
                    outer.db.log.log("WARNING", "connection",
                                     "client disconnected mid-exchange")
                finally:
                    if watcher is not None:
                        watcher.shutdown()

            def _control(self, req: dict) -> dict:
                """Protocol control ops (never parsed as SQL): 'ps' lists
                in-flight statements, 'cancel' flags one by id, 'metrics'
                serves the Prometheus text exposition, 'trace' exports one
                statement's Chrome trace_event JSON from the trace ring."""
                op = req.get("op")
                if op == "ps":
                    from greengage_tpu.runtime.trace import TRACES

                    rows = REGISTRY.snapshot()
                    bs = getattr(outer.db, "_batch_server", None)
                    for r in rows:
                        # current execution phase from the trace registry
                        # (`gg ps` SPAN column): deepest open span + its
                        # elapsed ms, when the statement is traced
                        sp = TRACES.active_span(r["id"])
                        if sp is not None:
                            r["span"], r["span_ms"] = sp[0], round(sp[1], 1)
                        # batched-serving membership (`gg ps` BATCH
                        # column): which flush window this statement is
                        # riding, when it is riding one
                        if bs is not None:
                            bid = bs.member_of(r["id"])
                            if bid is not None:
                                r["batch"] = bid
                    return {"ok": True, "rows": rows,
                            "cluster": _cluster_status(outer.db),
                            "pipeline": _pipeline_depths(outer.db),
                            "overload": _overload.CONTROLLER.snapshot(),
                            "ingest": outer.db.ingest.stream_status()}
                if op == "metrics":
                    # Prometheus text exposition over the process-wide
                    # counters/gauges/histograms (`gg metrics`); host
                    # process gauges (RSS, fds, staging-pool depth,
                    # per-owner live bytes) refresh at scrape time
                    from greengage_tpu.runtime import memaccount
                    from greengage_tpu.runtime.logger import prometheus_text

                    memaccount.update_process_gauges()
                    return {"ok": True, "text": prometheus_text()}
                if op == "mem":
                    # the measured-memory surface (`gg mem`): device
                    # allocator stats, per-statement accounting trees,
                    # the runaway ledger, block-cache budget state, and
                    # per-executable measured footprints
                    from greengage_tpu.runtime import memaccount

                    return {"ok": True, "mem": memaccount.report(outer.db)}
                if op == "checkperf":
                    # the self-tuning surface (`gg checkperf --feedback`
                    # against a live server): per-plan-digest
                    # est-vs-actual error, with apply/reset sub-ops
                    fb = outer.db.feedback
                    if req.get("reset"):
                        fb.reset()
                        return {"ok": True, "reset": True}
                    out = {"ok": True}
                    if req.get("apply"):
                        out["applied"] = fb.apply_pending()
                    out["feedback"] = fb.report()
                    return out
                if op == "trace":
                    from greengage_tpu.runtime.trace import TRACES, to_chrome

                    tid = req.get("id")
                    if tid is None:
                        tr = TRACES.last()
                    else:
                        try:
                            tr = TRACES.get(int(tid))
                        except (TypeError, ValueError):
                            return {"ok": False,
                                    "error": "trace needs a numeric id"}
                    if tr is None:
                        return {"ok": False,
                                "error": f"no trace for statement {tid!r} "
                                         "(evicted from the ring, or "
                                         "tracing is disabled)"}
                    return {"ok": True, "trace": to_chrome(tr)}
                if op == "status":
                    # the server status frame: dispatch topology state
                    # (full / n-1 / degraded), FTS topology version, the
                    # reform/commit-path counter family, and the overload
                    # state (fresh evaluation: operators polling status
                    # must see current pressure, not the rate-limited
                    # statement-path sample)
                    from greengage_tpu.runtime.logger import counters as _c

                    _overload.CONTROLLER.evaluate(outer.db.settings,
                                                  force=True)
                    st = _cluster_status(outer.db)
                    st["counters"] = {
                        k: v for k, v in _c.snapshot().items()
                        if k.startswith(("mh_", "manifest_", "batch_",
                                         "server_", "connections_",
                                         "admission_", "brownout",
                                         "frames_", "standby_"))}
                    st["counters"].update({
                        k: v for k, v in _c.snapshot().items()
                        if k.startswith("ingest_")})
                    return {"ok": True, "cluster": st,
                            "pipeline": _pipeline_depths(outer.db),
                            "overload": _overload.CONTROLLER.snapshot(),
                            "ingest": outer.db.ingest.stream_status()}
                if op == "cancel":
                    try:
                        sid = int(req.get("id"))
                    except (TypeError, ValueError):
                        return {"ok": False,
                                "error": "cancel needs a numeric id"}
                    if REGISTRY.cancel(sid, "user"):
                        outer.db.log.info(
                            "cancel", f"statement {sid} cancelled by "
                            "operator request")
                        return {"ok": True}
                    return {"ok": False,
                            "error": f"no in-flight statement {sid}"}
                # streaming ingest plane (runtime/ingest.py): long-lived
                # micro-batch COPY sessions; AdmissionShed raised here is
                # mapped by _serve into the typed retryable 53300 frame
                if op == "stream_begin":
                    out = outer.db.ingest.stream_begin(
                        req.get("table"), req.get("stream"))
                    return {"ok": True, **out}
                if op == "stream_rows":
                    seq = req.get("seq")
                    # a missing seq must NOT default to 0: feed() treats
                    # seq <= acked_seq as a resume replay and acks it as
                    # a duplicate — silently dropping the frame's rows
                    if isinstance(seq, bool) or not isinstance(seq, int):
                        return {"ok": False,
                                "error": "stream_rows requires an integer"
                                         " 'seq' (batch sequence number)"}
                    out = outer.db.ingest.stream_rows(
                        req.get("stream"), req.get("columns") or {}, seq)
                    return {"ok": True, **out}
                if op == "stream_end":
                    out = outer.db.ingest.stream_end(req.get("stream"))
                    return {"ok": True, **out}
                return {"ok": False, "error": f"unknown op {op!r}"}

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True
            # a connect storm must reach the TYPED shed path, not the
            # kernel's tiny default backlog (refused connects can't be
            # told to back off); sheds are one frame + close, so a deep
            # accept queue drains in microseconds
            request_queue_size = 128

        self._server = Server(self.socket_path, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gg-server", daemon=True)
        self._thread.start()

        if self.host is not None and self.port is not None:
            class TcpHandler(Handler):
                REMOTE = True

            class TcpServer(socketserver.ThreadingTCPServer):
                daemon_threads = True
                allow_reuse_address = True
                request_queue_size = 128   # accept-then-shed, as above

            self._tcp_server = TcpServer((self.host, self.port), TcpHandler)
            self.port = self._tcp_server.server_address[1]  # resolve port 0
            self._tcp_thread = threading.Thread(
                target=self._tcp_server.serve_forever, name="gg-server-tcp",
                daemon=True)
            self._tcp_thread.start()

    def stop(self) -> None:
        """Graceful drain (docs/ROBUSTNESS.md "Overload protection"):

        1. flag draining and stop accepting (new connects shed typed);
        2. flag every in-flight statement ``shutdown`` via the interrupt
           registry and SHUT_RD the client sockets — idle readers wake
           with EOF immediately, in-flight statements die at their next
           cancellation point and still flush their typed error (writes
           stay open);
        3. join every handler thread, bounded by ``server_drain_s``;
        4. force-close straggler sockets and join once more — no daemon
           thread is left parked on a socket the process is abandoning
           (a thread still inside an XLA dispatch finishes its program
           and exits at the next cancellation point)."""
        drain_s = max(float(getattr(self.db.settings,
                                    "server_drain_s", 5.0)), 0.0)
        with self._conn_mu:
            self._draining = True
            conns = dict(self._conns)
            handlers = dict(self._handlers)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._tcp_server is not None:
            self._tcp_server.shutdown()
            self._tcp_server.server_close()
            self._tcp_server = None
        for ident, sock in conns.items():
            REGISTRY.cancel_thread(ident, "shutdown")
            try:
                sock.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        deadline = time.monotonic() + drain_s
        for t in handlers.values():
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        leftover = [t for t in handlers.values() if t.is_alive()]
        if leftover:
            with self._conn_mu:
                socks = [self._conns[t.ident] for t in leftover
                         if t.ident in self._conns]
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
            for t in leftover:
                t.join(timeout=1.0)
            still = sum(1 for t in leftover if t.is_alive())
            if still:
                self.db.log.log(
                    "WARNING", "overload",
                    f"drain deadline ({drain_s:g}s) expired with {still} "
                    "connection(s) still closing")
        # open ingest streams flush-or-abort once their handlers are gone:
        # nothing buffered is silently abandoned, and the plane stays up
        # for Database.close() to stop for real
        try:
            self.db.ingest.drain_all()
        except Exception:
            pass
        # _draining stays set: a straggler handler past the deadline must
        # not serve another statement on a server that no longer accepts
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)


class SqlClient:
    """Tiny client for the line protocol (the psql/libpq stand-in).
    Local: SqlClient(path). Remote: SqlClient(host=..., port=...,
    user=..., password=...) — challenge-response, password never sent."""

    def __init__(self, socket_path: str | None = None, *,
                 host: str | None = None, port: int | None = None,
                 user: str = "", password: str = ""):
        if socket_path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(socket_path)
            self._f = self._sock.makefile("rwb")
        else:
            from greengage_tpu.runtime import auth

            self._sock = socket.create_connection((host, port))
            self._f = self._sock.makefile("rwb")
            self._f.write((json.dumps({"user": user}) + "\n").encode())
            self._f.flush()
            ch = json.loads(self._f.readline())
            if not ch.get("ok", True) and ch.get("code"):
                # typed connection shed (too_many_connections /
                # shutting_down) arrived instead of the auth challenge
                self._sock.close()
                raise ConnectionRefusedError(ch.get("error", "shed"))
            proof = auth.prove(ch["salt"], ch["nonce"], password)
            self._f.write((json.dumps({"proof": proof}) + "\n").encode())
            self._f.flush()
            resp = json.loads(self._f.readline())
            if not resp.get("ok"):
                self._sock.close()
                raise PermissionError(resp.get("error", "auth failed"))

    def sql(self, text: str):
        self._f.write((json.dumps({"sql": text}) + "\n").encode())
        self._f.flush()
        resp = json.loads(self._f.readline())
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "server error"))
        return resp

    def op(self, payload: dict) -> dict:
        """Send a control frame (ps/cancel) and return the raw response
        (not raising on ok=false — 'no such statement' is an answer)."""
        self._f.write((json.dumps(payload) + "\n").encode())
        self._f.flush()
        return json.loads(self._f.readline())

    def close(self):
        self._f.close()
        self._sock.close()
