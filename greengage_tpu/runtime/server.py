"""Line-protocol SQL server — the postmaster/libpq listener analog.

A thin concurrent front end over one Database: clients connect to a unix
socket (or TCP port) and exchange newline-delimited JSON:

    -> {"sql": "select ..."}
    <- {"ok": true, "columns": [...], "rows": [[...], ...], "tag": null}
    <- {"ok": false, "error": "..."}

Control frames ride the same protocol (the pg_stat_activity /
pg_cancel_backend surface, served from ANOTHER connection since the
executing one is blocked in its statement):

    -> {"op": "ps"}            <- {"ok": true, "rows": [activity...]}
    -> {"op": "cancel", "id": N}  <- {"ok": true/false}
    -> {"op": "mem"}           <- {"ok": true, "mem": {device/accounts...}}

Reference parity: exec_simple_query serving many clients
(src/backend/tcop/postgres.c:1622). Each connection gets a thread; SELECTs
run lock-free on manifest snapshots, write statements serialize on the
session write lock (one writer gang at a time), so concurrent COPY +
SELECT + UPDATE interleave safely. Transaction state is per connection
(the Database keeps one DtmSession per thread, and each connection is a
thread), so BEGIN/COMMIT/ROLLBACK work over the wire; a connection that
drops mid-transaction is rolled back, like a backend exiting — and a
disconnect observed mid-exchange cancels the connection's in-flight
statement with cause ``client_gone`` instead of letting the broken-pipe
error escape into socketserver. Conflicting commits fail at the manifest
CAS with a serialization error.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import select

from greengage_tpu.runtime.interrupt import REGISTRY, StatementCancelled


def _watch_client(sock, thread_ident: int, stop: "threading.Event") -> None:
    """Per-statement disconnect watcher: while the handler thread is
    blocked inside db.sql(), peek the client socket — an EOF means the
    client is gone, and the in-flight statement is flagged client_gone so
    it dies at its next cancellation point instead of running to
    completion for nobody. A readable socket with DATA is a pipelined
    request (client alive): stop watching, never consume it."""

    while not stop.wait(0.1):
        try:
            r, _, _ = select.select([sock], [], [], 0)
            if not r:
                continue
            if sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b"":
                REGISTRY.cancel_thread(thread_ident, "client_gone")
                return
            return            # buffered pipelined request: still alive
        except (BlockingIOError, InterruptedError):
            continue
        except OSError:
            REGISTRY.cancel_thread(thread_ident, "client_gone")
            return


def _pipeline_depths(db) -> dict:
    """Serving-pipeline queue depths for the ps/status frames: members
    waiting in batched-serving admission windows, batches staged-but-not-
    demuxed, and the staging pool's read-unit backlog (the PR-10
    staging_pool_queue_depth probe, reused rather than re-measured)."""
    from greengage_tpu.exec import staging

    out = {"staging_pool_queue_depth": staging.pool_queue_depth()}
    bs = getattr(db, "_batch_server", None)
    if bs is not None:
        try:
            out.update(bs.queue_depths())
        except Exception:
            pass
    return out


def _cluster_status(db) -> dict:
    """Topology state for the ps/status control frames; resilient to a
    Database predating mh_state (bare test doubles)."""
    try:
        return db.mh_state()
    except Exception:
        return {"state": "unknown", "topology_version": None}


def _encode_value(v):
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return str(v)


class SqlServer:
    def __init__(self, db, socket_path: str, host: str | None = None,
                 port: int | None = None):
        self.db = db
        self.socket_path = socket_path
        self.host, self.port = host, port
        self._server = None
        self._tcp_server = None
        self._thread = None
        self._tcp_thread = None
        self.connections_served = 0

    # ------------------------------------------------------------------
    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            REMOTE = False   # TCP subclass flips this: remote => auth

            def handle(self):
                outer.connections_served += 1
                try:
                    if self.REMOTE and not self._authenticate():
                        return
                    self._serve()
                finally:
                    # a connection dropping mid-transaction rolls back, and
                    # its cursors close, like a libpq backend exiting
                    outer.db.abort_if_active()
                    outer.db.close_thread_cursors()

            def _authenticate(self) -> bool:
                """Challenge-response over TCP (auth.c role): unix-socket
                peers are trusted, remote peers must prove a gg_hba.json
                password without sending it (runtime/auth.py)."""
                from greengage_tpu.runtime import auth

                users = auth.load_users(outer.db.path)
                ok = False
                try:
                    hello = json.loads(self.rfile.readline() or b"{}")
                    user = str(hello.get("user", ""))
                    ch = auth.challenge(users, user, outer.db.path)
                    self.wfile.write((json.dumps(ch) + "\n").encode())
                    self.wfile.flush()
                    resp = json.loads(self.rfile.readline() or b"{}")
                    ok = auth.verify(users, user, ch["nonce"],
                                     str(resp.get("proof", "")))
                    self.wfile.write((json.dumps(
                        {"ok": ok, "error": None if ok
                         else "authentication failed"}) + "\n").encode())
                    self.wfile.flush()
                except Exception:
                    # dropped peers and malformed handshakes must not
                    # traceback per port-scan probe
                    ok = False
                if not ok:
                    outer.db.log.log("WARNING", "auth",
                                     "remote authentication failed")
                return ok

            def _serve(self):
                me = threading.get_ident()
                try:
                    for line in self.rfile:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            req = json.loads(line)
                            if "op" in req and "sql" not in req:
                                resp = self._control(req)
                            else:
                                # watch for a mid-statement disconnect:
                                # this thread is blocked in db.sql(), so
                                # only a peeker can observe the EOF and
                                # flag the statement client_gone
                                stop = threading.Event()
                                wt = threading.Thread(
                                    target=_watch_client,
                                    args=(self.connection, me, stop),
                                    daemon=True, name="gg-client-watch")
                                wt.start()
                                try:
                                    out = outer.db.sql(req["sql"])
                                finally:
                                    stop.set()
                                    wt.join(timeout=2)
                                if isinstance(out, str) or out is None:
                                    resp = {"ok": True, "columns": None,
                                            "rows": None, "tag": out}
                                else:
                                    resp = {
                                        "ok": True,
                                        "columns": list(out.columns),
                                        "rows": [[_encode_value(v)
                                                  for v in row]
                                                 for row in out.rows()],
                                        "tag": None,
                                    }
                        except StatementCancelled as e:
                            # surface the typed cause to the client (the
                            # '57014 query_canceled' SQLSTATE analog)
                            resp = {"ok": False, "error": f"{e}",
                                    "cancelled": e.cause}
                        except Exception as e:  # per-statement isolation
                            resp = {"ok": False, "error": f"{e}"}
                        self.wfile.write((json.dumps(resp) + "\n").encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # the client vanished mid-exchange: flag whatever this
                    # connection still has in flight as client_gone and
                    # end the handler cleanly — a disconnect must never
                    # traceback into socketserver (the statement dies at
                    # its next cancellation point and nobody reads the
                    # error)
                    REGISTRY.cancel_thread(me, "client_gone")
                    outer.db.log.log("WARNING", "connection",
                                     "client disconnected mid-exchange")

            def _control(self, req: dict) -> dict:
                """Protocol control ops (never parsed as SQL): 'ps' lists
                in-flight statements, 'cancel' flags one by id, 'metrics'
                serves the Prometheus text exposition, 'trace' exports one
                statement's Chrome trace_event JSON from the trace ring."""
                op = req.get("op")
                if op == "ps":
                    from greengage_tpu.runtime.trace import TRACES

                    rows = REGISTRY.snapshot()
                    bs = getattr(outer.db, "_batch_server", None)
                    for r in rows:
                        # current execution phase from the trace registry
                        # (`gg ps` SPAN column): deepest open span + its
                        # elapsed ms, when the statement is traced
                        sp = TRACES.active_span(r["id"])
                        if sp is not None:
                            r["span"], r["span_ms"] = sp[0], round(sp[1], 1)
                        # batched-serving membership (`gg ps` BATCH
                        # column): which flush window this statement is
                        # riding, when it is riding one
                        if bs is not None:
                            bid = bs.member_of(r["id"])
                            if bid is not None:
                                r["batch"] = bid
                    return {"ok": True, "rows": rows,
                            "cluster": _cluster_status(outer.db),
                            "pipeline": _pipeline_depths(outer.db)}
                if op == "metrics":
                    # Prometheus text exposition over the process-wide
                    # counters/gauges/histograms (`gg metrics`); host
                    # process gauges (RSS, fds, staging-pool depth,
                    # per-owner live bytes) refresh at scrape time
                    from greengage_tpu.runtime import memaccount
                    from greengage_tpu.runtime.logger import prometheus_text

                    memaccount.update_process_gauges()
                    return {"ok": True, "text": prometheus_text()}
                if op == "mem":
                    # the measured-memory surface (`gg mem`): device
                    # allocator stats, per-statement accounting trees,
                    # the runaway ledger, block-cache budget state, and
                    # per-executable measured footprints
                    from greengage_tpu.runtime import memaccount

                    return {"ok": True, "mem": memaccount.report(outer.db)}
                if op == "trace":
                    from greengage_tpu.runtime.trace import TRACES, to_chrome

                    tid = req.get("id")
                    if tid is None:
                        tr = TRACES.last()
                    else:
                        try:
                            tr = TRACES.get(int(tid))
                        except (TypeError, ValueError):
                            return {"ok": False,
                                    "error": "trace needs a numeric id"}
                    if tr is None:
                        return {"ok": False,
                                "error": f"no trace for statement {tid!r} "
                                         "(evicted from the ring, or "
                                         "tracing is disabled)"}
                    return {"ok": True, "trace": to_chrome(tr)}
                if op == "status":
                    # the server status frame: dispatch topology state
                    # (full / n-1 / degraded), FTS topology version, and
                    # the reform/commit-path counter family
                    from greengage_tpu.runtime.logger import counters

                    st = _cluster_status(outer.db)
                    st["counters"] = {
                        k: v for k, v in counters.snapshot().items()
                        if k.startswith(("mh_", "manifest_", "batch_"))}
                    return {"ok": True, "cluster": st,
                            "pipeline": _pipeline_depths(outer.db)}
                if op == "cancel":
                    try:
                        sid = int(req.get("id"))
                    except (TypeError, ValueError):
                        return {"ok": False,
                                "error": "cancel needs a numeric id"}
                    if REGISTRY.cancel(sid, "user"):
                        outer.db.log.info(
                            "cancel", f"statement {sid} cancelled by "
                            "operator request")
                        return {"ok": True}
                    return {"ok": False,
                            "error": f"no in-flight statement {sid}"}
                return {"ok": False, "error": f"unknown op {op!r}"}

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(self.socket_path, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gg-server", daemon=True)
        self._thread.start()

        if self.host is not None and self.port is not None:
            class TcpHandler(Handler):
                REMOTE = True

            class TcpServer(socketserver.ThreadingTCPServer):
                daemon_threads = True
                allow_reuse_address = True

            self._tcp_server = TcpServer((self.host, self.port), TcpHandler)
            self.port = self._tcp_server.server_address[1]  # resolve port 0
            self._tcp_thread = threading.Thread(
                target=self._tcp_server.serve_forever, name="gg-server-tcp",
                daemon=True)
            self._tcp_thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._tcp_server is not None:
            self._tcp_server.shutdown()
            self._tcp_server.server_close()
            self._tcp_server = None
        if os.path.exists(self.socket_path):
            os.remove(self.socket_path)


class SqlClient:
    """Tiny client for the line protocol (the psql/libpq stand-in).
    Local: SqlClient(path). Remote: SqlClient(host=..., port=...,
    user=..., password=...) — challenge-response, password never sent."""

    def __init__(self, socket_path: str | None = None, *,
                 host: str | None = None, port: int | None = None,
                 user: str = "", password: str = ""):
        if socket_path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(socket_path)
            self._f = self._sock.makefile("rwb")
        else:
            from greengage_tpu.runtime import auth

            self._sock = socket.create_connection((host, port))
            self._f = self._sock.makefile("rwb")
            self._f.write((json.dumps({"user": user}) + "\n").encode())
            self._f.flush()
            ch = json.loads(self._f.readline())
            proof = auth.prove(ch["salt"], ch["nonce"], password)
            self._f.write((json.dumps({"proof": proof}) + "\n").encode())
            self._f.flush()
            resp = json.loads(self._f.readline())
            if not resp.get("ok"):
                self._sock.close()
                raise PermissionError(resp.get("error", "auth failed"))

    def sql(self, text: str):
        self._f.write((json.dumps({"sql": text}) + "\n").encode())
        self._f.flush()
        resp = json.loads(self._f.readline())
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "server error"))
        return resp

    def op(self, payload: dict) -> dict:
        """Send a control frame (ps/cancel) and return the raw response
        (not raising on ok=false — 'no such statement' is an answer)."""
        self._f.write((json.dumps(payload) + "\n").encode())
        self._f.flush()
        return json.loads(self._f.readline())

    def close(self):
        self._f.close()
        self._sock.close()
