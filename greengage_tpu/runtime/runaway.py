"""Mid-flight memory enforcement — the vmem tracker + red-zone handler +
runaway cleaner roles
(/root/reference/src/backend/utils/mmgr/vmem_tracker.c,
 redzone_handler.c, runaway_cleaner.c:1) rethought for the XLA execution
model.

The reference interposes on every palloc and, at 90% of gp_vmem_protect,
the red-zone handler picks the session holding the most vmem and the
runaway cleaner cancels it at its next CHECK_FOR_INTERRUPTS. Under XLA a
statement's device footprint is decided at COMPILE time (static buffers),
so the tracker ledgers each in-flight statement's compiled estimate, and
the red-zone check runs at the same admission point — but against the
CLUSTER-WIDE in-flight total, which single-statement admission cannot
see. Crossing the red zone flags the heaviest in-flight statement; it
terminates at its next cancellation point (a retry-tier boundary or a
spill pass boundary — the XLA analog of CHECK_FOR_INTERRUPTS, since a
dispatched device program cannot be preempted mid-flight).

Statement identity is the executing thread: nested executor runs (spill
passes) share their statement's ledger entry, keeping the whole spilled
statement one cancellable unit.
"""

from __future__ import annotations

import threading
import time

from greengage_tpu.runtime.interrupt import REGISTRY, StatementCancelled


class RunawayCancelled(StatementCancelled):
    """The statement was chosen by the runaway cleaner. A
    StatementCancelled with cause 'runaway': the cleaner is one producer
    of the unified per-statement interrupt flag (runtime/interrupt.py),
    so sessions count and surface it like every other cancellation."""

    def __init__(self, message: str):
        super().__init__(message, "runaway")


class _Entry:
    __slots__ = ("bytes", "cancel_reason", "depth", "flag_time", "ctx",
                 "measured")

    def __init__(self, nbytes: int, ctx=None):
        self.bytes = nbytes
        # True once the price came from the executable's XLA
        # memory_analysis instead of the planner estimate (warm
        # executables under mem_accounting_enabled) — the cleaner then
        # arbitrates on ground truth, and `gg mem` shows which
        self.measured = False
        self.cancel_reason: str | None = None
        self.depth = 1          # nested executor runs (spill passes)
        self.flag_time = 0.0
        # the statement's interrupt context (when one is registered):
        # flagging the victim ALSO sets the unified cancel flag, so every
        # cancellation point (staging, queue, spill) observes it — not
        # just the tracker's own check()
        self.ctx = ctx


class VmemTracker:
    """Process-wide in-flight ledger keyed by executing thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, _Entry] = {}

    # ---- statement lifecycle -----------------------------------------
    def enter(self) -> None:
        """Register (or re-enter, for nested spill-pass runs) the calling
        thread's statement."""
        tid = threading.get_ident()
        ctx = REGISTRY.current()
        with self._lock:
            cur = self._active.get(tid)
            if cur is not None:
                cur.depth += 1
            else:
                self._active[tid] = _Entry(0, ctx)

    def reprice(self, est_bytes: int, global_limit_bytes: int,
                red_zone: float, measured: bool = False) -> None:
        """Record this statement's current compiled estimate, then run the
        red-zone scan: when the cluster-wide total crosses the zone, flag
        the HEAVIEST in-flight statement for termination
        (runaway_cleaner.c picks the top consumer); it dies at its next
        cancellation point. If the caller IS the top consumer, the flag
        lands on itself."""
        tid = threading.get_ident()
        with self._lock:
            cur = self._active.get(tid)
            if cur is None:
                return
            # last-write, not max: once a statement enters the spill
            # regime its footprint IS the per-pass estimate — the
            # rejected whole-plan estimate was never allocated
            cur.bytes = est_bytes
            cur.measured = bool(measured)
            if not global_limit_bytes:
                return
            total = sum(e.bytes for e in self._active.values())
            if total <= red_zone * global_limit_bytes:
                return

            now = time.monotonic()
            if any(e.cancel_reason is not None and now - e.flag_time < 10.0
                   for e in self._active.values()):
                return   # a victim is dying; its bytes release soon. A
                # STALE flag (victim past its last cancellation point)
                # must not disable enforcement forever, so it ages out
            victim = None
            for t, e in self._active.items():
                if t == tid or e.cancel_reason is not None:
                    continue
                if victim is None or e.bytes > victim.bytes:
                    victim = e
            if victim is None or victim.bytes < cur.bytes:
                if len(self._active) == 1:
                    # alone over the zone is not CONTENTION — the
                    # per-statement limit (admission/spill) governs a
                    # lone statement; the cleaner only arbitrates between
                    # statements
                    return
                if cur.bytes < max(e.bytes for e in self._active.values()):
                    # the true top consumer already carries a (stale)
                    # flag; cancelling a lighter newcomer frees nothing
                    return
                victim = cur   # newcomer is the top consumer under
                # contention: it takes the cancellation (runaway_cleaner
                # picks the largest)
            target = victim
            target.flag_time = now
            target.cancel_reason = (
                f"canceled by the runaway cleaner: cluster in-flight device "
                f"memory ~{total >> 20} MB crossed the red zone "
                f"({red_zone:.0%} of {global_limit_bytes >> 20} MB) and this "
                f"statement was the top consumer (~{target.bytes >> 20} MB)")
            if target.ctx is not None:
                # unified cancellation: the victim dies at ANY of its
                # cancellation points (staging unit, queue wait, spill
                # boundary), not only at the tracker's own check()
                target.ctx.cancel("runaway", target.cancel_reason)

    def check(self) -> None:
        """Cancellation point: raise if this thread's statement was picked
        (CHECK_FOR_INTERRUPTS analog)."""
        tid = threading.get_ident()
        with self._lock:
            e = self._active.get(tid)
            reason = e.cancel_reason if e is not None else None
        if reason is not None:
            raise RunawayCancelled(reason)

    def release(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            e = self._active.get(tid)
            if e is None:
                return
            e.depth -= 1
            if e.depth <= 0:
                del self._active[tid]

    # ---- observability (gp_toolkit vmem views role) -------------------
    def snapshot(self) -> list[dict]:
        with self._lock:
            return [{"thread": t, "bytes": e.bytes,
                     "measured": e.measured,
                     "statement_id": (e.ctx.statement_id
                                      if e.ctx is not None else None),
                     "flagged": e.cancel_reason is not None}
                    for t, e in self._active.items()]


TRACKER = VmemTracker()
