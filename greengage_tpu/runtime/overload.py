"""Memory-pressure brownout controller — degrade-before-die for the
serving plane (docs/ROBUSTNESS.md "Overload protection").

The reference engine survives sustained overload by *shedding work in
layers* before anything dies: connection limits at the postmaster,
queue rejection (SQLSTATE 53300) at admission, and the vmem red zone
mid-flight. The bounded front end (runtime/server.py) and the
admission-queue shed (runtime/resqueue.py) cover the first two; this
module supplies the third, memory-shaped layer: a typed BROWNOUT state
the engine enters when device-memory pressure says the next admission
is likely to OOM, and exits with hysteresis once pressure clears.

Pressure signals (evaluated by ``OverloadController.evaluate``, cheap
and rate-limited — one device allocator probe per ~quarter second):

  * live HBM watermarks — ``memaccount.device_memory_stats()``
    ``bytes_in_use / bytes_limit`` at/above ``brownout_enter_pct``
    (the red-zone fraction); while IN brownout the bar drops to
    ``brownout_exit_pct``, the classic hysteresis band, so the state
    cannot flap across a single allocation;
  * OOM streaks — ``brownout_oom_events`` classified device
    RESOURCE_EXHAUSTED events (the PR-10 ``oom_events`` counter) within
    ``brownout_window_s`` — repeated OOMs mean admission estimates are
    systematically optimistic, whatever the watermark claims;
  * the ``brownout_force`` fault point — deterministic drills in tests
    and ops runbooks (arm with type ``skip``, occurrences=-1).

Effects while browned out (all pull-based — consumers read the
controller, nothing holds references to every Database):

  * the block-cache byte budget shrinks to ``brownout_cache_factor`` of
    ``scan_cache_limit_mb`` (storage/blockcache.py reads
    ``cache_factor()`` live; the session evicts to the shrunken budget
    on the transition edge);
  * batch serving is disabled — new statements take the classic serial
    path (``Database._batch_eligible`` consults ``brownout_active()``);
    stacking member params multiplies footprints exactly when HBM has
    no headroom;
  * new admissions prefer the spill tier: the executor scales its
    admission ceiling by ``brownout_vmem_factor`` (single-host only —
    the factor is process-local state and would desync multihost
    lockstep spill decisions).

Exit is hysteretic twice over: the watermark bar drops to the exit
fraction, AND every signal must stay clear for ``brownout_exit_s``
before the state clears — a brownout that un-sheds the moment its own
shedding freed memory would oscillate.

The controller is process-wide (``CONTROLLER``), like the counters and
the interrupt registry: the device HBM it models is a process-wide
resource shared by every Database in the process. State transitions
land in ``brownout_entered_total`` / ``brownout_exited_total`` and the
``brownout`` gauge; ``snapshot()`` feeds ``{"op":"status"}``, ``gg ps``
and the tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from greengage_tpu.runtime import lockdebug
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.logger import counters


class OverloadController:
    """The brownout state machine. Thread-safe: any statement thread may
    evaluate; server control frames read snapshots concurrently."""

    MIN_EVAL_S = 0.25   # device allocator probe rate limit

    def __init__(self):
        self._lock = lockdebug.named(threading.Lock(), "overload._lock")
        self._brownout = False
        self._reason: str | None = None
        self._entered_at = 0.0
        self._clear_since: float | None = None
        self._last_eval = 0.0
        self._cache_factor = 1.0
        self._vmem_factor = 1.0
        # (monotonic time, oom_events counter value) samples inside the
        # sliding window — the streak detector's memory
        self._oom_marks: deque = deque()

    # ---- consumers (pull-based effects) ------------------------------
    def brownout_active(self) -> bool:
        with self._lock:
            return self._brownout

    def cache_factor(self) -> float:
        """Multiplier for the block-cache byte budget (1.0 = normal).
        Read live by CacheRegistry.limit_bytes under the registry lock."""
        with self._lock:
            return self._cache_factor if self._brownout else 1.0

    def scaled_vmem(self, limit_bytes: int) -> int:
        """Brownout-scaled per-query admission ceiling: a smaller limit
        routes borderline statements to the spill tier instead of racing
        a pressured allocator. 0 (unlimited) stays 0 — the operator
        disabled the guard explicitly."""
        with self._lock:
            if not self._brownout or limit_bytes <= 0:
                return limit_bytes
            return max(int(limit_bytes * self._vmem_factor), 1 << 20)

    # ---- evaluation ---------------------------------------------------
    def evaluate(self, settings, force: bool = False) -> bool:
        """Run the state machine once (rate-limited unless ``force``);
        returns the post-evaluation brownout state. Callers compare
        against their last-seen state to apply edge effects (prompt
        cache eviction, logging)."""
        now = time.monotonic()
        with self._lock:
            if not force and (now - self._last_eval) < self.MIN_EVAL_S:
                return self._brownout
            self._last_eval = now
            in_brownout = self._brownout
        if not bool(getattr(settings, "brownout_enabled", True)):
            pressure, reason = False, None
        else:
            pressure, reason = self._pressure(settings, now, in_brownout)
        with self._lock:
            if pressure:
                self._clear_since = None
                if not self._brownout:
                    self._brownout = True
                    self._reason = reason
                    self._entered_at = now
                    counters.inc("brownout_entered_total")
                    counters.set("brownout", 1)
            elif self._brownout:
                if self._clear_since is None:
                    self._clear_since = now
                if (now - self._clear_since) >= float(getattr(
                        settings, "brownout_exit_s", 5.0)):
                    self._brownout = False
                    self._reason = None
                    self._clear_since = None
                    counters.inc("brownout_exited_total")
                    counters.set("brownout", 0)
            if self._brownout:
                # refresh the effect factors from settings EVERY
                # evaluation, not just on entry: `SET
                # brownout_cache_factor = 0.2` during a live incident
                # must change the budget at the next evaluation (the
                # GUCS.md "read live" contract), not after a re-entry
                self._cache_factor = _clamp(getattr(
                    settings, "brownout_cache_factor", 0.5))
                self._vmem_factor = _clamp(getattr(
                    settings, "brownout_vmem_factor", 0.5))
            return self._brownout

    def _pressure(self, settings, now: float,
                  in_brownout: bool) -> tuple[bool, str | None]:
        """One pressure reading across all three signals. Runs OUTSIDE
        the controller lock (device probe + fault registry have their
        own locks); only the OOM-mark deque re-enters briefly."""
        # deterministic drills: treat any firing type as forced pressure
        # (an 'error' injection must force the state, not fail a query)
        try:
            forced = faults.check("brownout_force")
        except FaultError:
            forced = True
        if forced:
            return True, "forced by fault injection (brownout_force)"
        # live HBM watermark vs the hysteresis band
        from greengage_tpu.runtime import memaccount

        stats = memaccount.device_memory_stats()
        if stats:
            cap = int(stats.get("bytes_limit", 0) or 0)
            used = int(stats.get("bytes_in_use", 0) or 0)
            if cap > 0:
                frac = used / cap
                bar = float(getattr(settings, "brownout_exit_pct", 0.80)
                            if in_brownout else
                            getattr(settings, "brownout_enter_pct", 0.92))
                if frac >= bar:
                    return True, (
                        f"device memory {frac:.0%} of HBM "
                        f"({used >> 20}/{cap >> 20} MB) at/above "
                        f"{bar:.0%}")
        # classified-OOM streak inside the sliding window
        window = max(float(getattr(settings, "brownout_window_s", 30.0)),
                     0.001)
        threshold = int(getattr(settings, "brownout_oom_events", 3))
        oom_now = counters.get("oom_events")
        with self._lock:
            self._oom_marks.append((now, oom_now))
            while self._oom_marks and \
                    (now - self._oom_marks[0][0]) > window:
                self._oom_marks.popleft()
            delta = oom_now - self._oom_marks[0][1]
        if threshold > 0 and delta >= threshold:
            return True, (f"{delta} device OOM events within "
                          f"{window:g}s (brownout_oom_events="
                          f"{threshold})")
        return False, None

    # ---- observability -----------------------------------------------
    def snapshot(self) -> dict:
        """The status-frame payload ({"op":"status"}, `gg ps`, tests)."""
        now = time.monotonic()
        with self._lock:
            return {
                "brownout": self._brownout,
                "reason": self._reason,
                "since_s": (round(now - self._entered_at, 3)
                            if self._brownout else None),
                "cache_factor": (self._cache_factor if self._brownout
                                 else 1.0),
                "batch_serving_disabled": self._brownout,
            }

    def reset(self) -> None:
        """Test teardown: drop to the normal state and zero the gauge so
        one test's forced brownout cannot leak into the next."""
        with self._lock:
            was = self._brownout
            self._brownout = False
            self._reason = None
            self._clear_since = None
            self._last_eval = 0.0
            self._oom_marks.clear()
            if was:
                counters.set("brownout", 0)


def _clamp(v, lo: float = 0.05, hi: float = 1.0) -> float:
    return min(max(float(v), lo), hi)


CONTROLLER = OverloadController()   # process-wide, like counters/REGISTRY
