"""Measured memory accounting — the vmem_tracker.c + memaccounting.c
analog for the XLA execution model.

The reference's L0 is a *measured* substrate: ``vmem_tracker.c``
interposes on every palloc and ``memaccounting.c`` keeps a per-statement
owner tree that is dumped on OOM. Everything above it (red zone, runaway
cleaner, workfile spilling) keys off those measured numbers. Our engine's
vmem machinery ran for four PRs on planner *estimates* (node capacity x
dtype width) and never looked at what XLA actually allocated or what the
device actually holds. This module supplies the measured layer:

  * ``MemoryAccount`` — one per-statement owner tree (thread-keyed in
    ``ACCOUNTS``, exactly like the interrupt and trace registries; the
    account id IS the statement id). Owners are the fixed taxonomy in
    ``OWNERS``: host ``staging`` buffers, this statement's ``blockcache``
    inserts, ``spill`` run captures, and the executable's ``device``
    footprint (args/temps/output, measured by XLA when available).
    Charges from staging-pool threads ride an explicit ``bind()`` — the
    same discipline the interrupt context uses for pool reads.
  * ``jax`` executable measurement — the executor attaches
    ``compiled.memory_analysis()`` (temp/argument/output/generated-code
    bytes) to every cached executable at first dispatch and REUSES it on
    warm hits (``mem_analysis_runs`` counts the analyses, so tests can
    assert a warm hit re-analyzes nothing). The estimate-vs-measured
    error lands in the ``mem_est_error_pct`` gauge — the first ground
    truth four PRs of capacity bucketing ever had.
  * live HBM watermarks — ``sample_watermark()`` reads
    ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``;
    gracefully None on CPU backends, after which sampling self-disables)
    and is installed as the trace substrate's span sampler, so `gg trace`
    shows the device-memory delta of every span.
  * OOM forensics — ``is_oom_error()`` classifies XLA RESOURCE_EXHAUSTED;
    the executor raises a typed ``OutOfDeviceMemory`` carrying the
    accounting snapshot + the offending executable's memory analysis, and
    the session dumps ``mem-<id>.json`` beside the slow-log traces.

Process-wide surfaces: per-owner live-byte gauges
(``mem_owner_bytes_<owner>``), device gauges (``device_bytes_in_use`` /
``device_peak_bytes_in_use``), host process gauges (RSS, open fds,
staging-pool queue depth) — all exported by `gg metrics`; `gg mem` /
the server ``{"op": "mem"}`` frame serve the full ``report()``.
"""

from __future__ import annotations

import os
import resource
import sys
import threading
from collections import OrderedDict
from contextlib import contextmanager

from greengage_tpu.runtime import trace as _trace
from greengage_tpu.runtime.logger import counters

# fixed owner taxonomy (docs/OBSERVABILITY.md "Memory accounting"): the
# per-owner gauges are declared per name in runtime/logger.py, so charges
# outside this set would be invisible to the exposition — charge()
# rejects them rather than losing bytes silently
OWNERS = ("staging", "blockcache", "spill", "device")

# keep the per-owner item detail bounded: a statement scanning thousands
# of partition children must degrade to a truncated item map, never to
# unbounded account growth
MAX_ITEMS_PER_OWNER = 64


class MemoryAccount:
    """One statement's per-owner memory tree. Thread-safe: the statement
    thread charges staging/spill/device, pool threads (via ``bind``)
    charge block-cache inserts concurrently."""

    def __init__(self, statement_id: int, sql: str = ""):
        self.statement_id = statement_id
        self.sql = (sql or "").strip()[:200]
        self.depth = 1            # nested sql() calls share it
        self._lock = threading.Lock()
        # set (under _lock) when the registry retires the account: a
        # straggler pool thread finishing a read unit after a cancelled
        # stage must not charge live bytes the exit already subtracted —
        # the gauge would drift upward for the life of the process
        self._closed = False
        # owner -> [current bytes, peak bytes, {item: bytes}]
        self._owners: dict[str, list] = {}

    def charge(self, owner: str, nbytes: int, item: str | None = None) -> None:
        if owner not in OWNERS:
            raise ValueError(f"unknown memory owner {owner!r} "
                             f"(taxonomy: {OWNERS})")
        nbytes = int(nbytes)
        # the live-total update happens under the SAME lock as the closed
        # check (lock order: account lock -> _owner_mu, nothing reverse),
        # so close() + subtraction can never interleave with a late add
        with self._lock:
            if self._closed:
                return
            ent = self._owners.get(owner)
            if ent is None:
                ent = self._owners[owner] = [0, 0, {}]
            ent[0] += nbytes
            ent[1] = max(ent[1], ent[0])
            if item is not None:
                items = ent[2]
                if item in items or len(items) < MAX_ITEMS_PER_OWNER:
                    items[item] = items.get(item, 0) + nbytes
                else:
                    items["<other>"] = items.get("<other>", 0) + nbytes
            _owner_live_add(owner, nbytes)

    def set_device(self, analysis: dict | None, est_bytes: int = 0) -> None:
        """Record the executable's device footprint: the measured
        memory_analysis when XLA reports one, the compiled estimate
        otherwise (items mark which)."""
        with self._lock:
            if self._closed:
                return
            ent = self._owners.get("device")
            if ent is None:
                ent = self._owners["device"] = [0, 0, {}]
            old = ent[0]
            if analysis:
                total = (analysis.get("argument_bytes", 0)
                         + analysis.get("temp_bytes", 0)
                         + analysis.get("output_bytes", 0))
                ent[2] = {"args": analysis.get("argument_bytes", 0),
                          "temp": analysis.get("temp_bytes", 0),
                          "output": analysis.get("output_bytes", 0),
                          "code": analysis.get("generated_code_bytes", 0)}
            else:
                total = int(est_bytes)
                ent[2] = {"estimate": total}
            ent[0] = total
            ent[1] = max(ent[1], total)
            _owner_live_add("device", ent[0] - old)

    def close(self) -> None:
        """Retire the account: refuse further charges and release its
        live bytes from the process-wide owner totals, atomically with
        respect to concurrent charges."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for owner, ent in self._owners.items():
                _owner_live_add(owner, -ent[0])

    def owner_totals(self) -> dict[str, int]:
        with self._lock:
            return {o: ent[0] for o, ent in self._owners.items()}

    def total_bytes(self) -> int:
        with self._lock:
            return sum(ent[0] for ent in self._owners.values())

    def snapshot(self) -> dict:
        """The full per-owner accounting tree — what an OOM dump and the
        `gg mem` report carry (MemoryAccounting_SaveToLog analog)."""
        with self._lock:
            owners = {o: {"bytes": ent[0], "peak_bytes": ent[1],
                          "items": dict(ent[2])}
                      for o, ent in self._owners.items()}
        return {"statement_id": self.statement_id, "sql": self.sql,
                "owners": owners,
                "total_bytes": sum(o["bytes"] for o in owners.values())}


class AccountRegistry:
    """Process-wide registry: in-flight accounts keyed by thread (one
    statement per connection thread, like the interrupt and trace
    registries) plus a small completed ring for `gg mem`."""

    RING = 16

    def __init__(self):
        self._lock = threading.Lock()
        self._by_thread: dict[int, MemoryAccount] = {}
        self._ring: OrderedDict[int, dict] = OrderedDict()

    def enter(self, statement_id: int, sql: str = "",
              enabled: bool = True) -> tuple[MemoryAccount | None, bool]:
        """Open (or re-enter) the calling thread's account; nested sql()
        calls share the outermost one. -> (account | None, is_outermost)."""
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is not None:
                cur.depth += 1
                return cur, False
            if not enabled:
                return None, True
            acct = MemoryAccount(statement_id, sql)
            self._by_thread[tid] = acct
            return acct, True

    def exit(self, acct: MemoryAccount | None) -> None:
        if acct is None:
            return
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is None:
                return
            cur.depth -= 1
            if cur.depth > 0:
                return
            del self._by_thread[tid]
            self._ring[cur.statement_id] = cur.snapshot()
            while len(self._ring) > self.RING:
                self._ring.popitem(last=False)
        # retire: live bytes leave the process-wide owner gauges and any
        # straggler pool thread's late charge becomes a no-op
        cur.close()

    def current(self) -> MemoryAccount | None:
        return self._by_thread.get(threading.get_ident())

    @contextmanager
    def bind(self, acct: MemoryAccount | None):
        """Register a POOL thread against a statement's account for the
        duration of one read unit (the interrupt ctx handoff discipline):
        block-cache inserts inside the unit then attribute correctly."""
        if acct is None:
            yield
            return
        tid = threading.get_ident()
        with self._lock:
            prev = self._by_thread.get(tid)
            self._by_thread[tid] = acct
        try:
            yield
        finally:
            with self._lock:
                if prev is None:
                    self._by_thread.pop(tid, None)
                else:
                    self._by_thread[tid] = prev

    def snapshot(self) -> list[dict]:
        with self._lock:
            # dedup by account identity: during a cold stage every bound
            # pool thread maps to the statement's ONE account, and
            # `gg mem` must not print that statement scan_threads+1 times
            accts = list({id(a): a for a in self._by_thread.values()}
                         .values())
        return [a.snapshot() for a in accts]

    def ring(self) -> list[dict]:
        with self._lock:
            return list(self._ring.values())


ACCOUNTS = AccountRegistry()   # process-wide (shmem MemoryAccounting role)


def charge(owner: str, nbytes: int, item: str | None = None) -> None:
    """Charge the calling thread's current account; a cheap no-op when
    accounting is off or the thread runs no statement."""
    acct = ACCOUNTS.current()
    if acct is not None:
        acct.charge(owner, nbytes, item)


# ---- process-wide per-owner live totals (the gauge source) -------------
_owner_mu = threading.Lock()
_OWNER_LIVE: dict[str, int] = {}


def _owner_live_add(owner: str, nbytes: int) -> None:
    with _owner_mu:
        _OWNER_LIVE[owner] = _OWNER_LIVE.get(owner, 0) + int(nbytes)


def owner_live_bytes() -> dict[str, int]:
    with _owner_mu:
        return {o: max(n, 0) for o, n in _OWNER_LIVE.items()}


# ---- device watermarks -------------------------------------------------
# memory_stats() returns None on backends without an HBM allocator (CPU);
# a clean None probe self-disables sampling so the per-span hook costs
# one flag read. Probe EXCEPTIONS are treated as transient (a TPU
# runtime hiccup must not permanently kill watermarks + measured
# admission) — only a streak of them latches the disable.
_dev_mu = threading.Lock()
_DEV_UNSUPPORTED = False
_DEV_FAILS = 0
_DEV_FAIL_LIMIT = 3
_dev_handle = None   # cached jax device: the sampler runs twice per span
# on allocator-bearing backends, so it must not pay a backend resolution
# (jax.local_devices()) per sample — one memory_stats() C call only


def device_memory_stats() -> dict | None:
    """First local device's allocator stats (bytes_in_use,
    peak_bytes_in_use, ...); None when the backend has none (CPU).
    All probe state (_dev_handle/_DEV_FAILS/_DEV_UNSUPPORTED) moves
    under _dev_mu; only the memory_stats() C call itself runs outside
    it, so concurrent samplers never see a half-updated handle
    (gg check races)."""
    global _DEV_UNSUPPORTED, _DEV_FAILS, _dev_handle
    with _dev_mu:
        if _DEV_UNSUPPORTED:
            return None
        d = _dev_handle
    try:
        if d is None:
            import jax

            devs = jax.local_devices()
            if not devs:
                with _dev_mu:
                    _DEV_UNSUPPORTED = True
                return None
            d = devs[0]
            with _dev_mu:
                _dev_handle = d
        stats = d.memory_stats()
    except Exception:
        with _dev_mu:
            _dev_handle = None   # re-resolve next probe (backend restart)
            _DEV_FAILS += 1
            if _DEV_FAILS >= _DEV_FAIL_LIMIT:
                _DEV_UNSUPPORTED = True
        return None
    if not stats:
        # a SUCCESSFUL probe reporting no allocator is the genuine
        # unsupported-backend answer: latch immediately
        with _dev_mu:
            _DEV_UNSUPPORTED = True
        return None
    with _dev_mu:
        _DEV_FAILS = 0
    return dict(stats)


def sample_watermark() -> int | None:
    """One live HBM sample -> bytes_in_use (None on CPU backends).
    Updates the device gauges as a side effect; installed as the trace
    substrate's span sampler so `gg trace` shows per-span deltas."""
    stats = device_memory_stats()
    if stats is None:
        return None
    used = int(stats.get("bytes_in_use", 0))
    counters.set("device_bytes_in_use", used)
    peak = stats.get("peak_bytes_in_use")
    if peak is not None:
        counters.set("device_peak_bytes_in_use", int(peak))
    return used


_trace.set_mem_sampler(sample_watermark)


# ---- OOM classification ------------------------------------------------
# NO bare "oom" marker: it substring-matches "bloom" (as in bloom-filter
# error text) and would misclassify unrelated failures
_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory",
                "out_of_memory", "allocation failure")


def is_oom_error(e: BaseException) -> bool:
    """Does this exception look like a device allocation failure? XLA
    surfaces them as XlaRuntimeError with a RESOURCE_EXHAUSTED status
    (BFC allocator: 'Out of memory while trying to allocate N bytes')."""
    s = f"{type(e).__name__}: {e}".lower()
    return any(m in s for m in _OOM_MARKERS)


# ---- host process gauges (`gg metrics` satellite) ----------------------
def _current_rss_bytes() -> int:
    """Current resident set: /proc/self/statm (field 2, pages) where it
    exists; elsewhere fall back to getrusage's ru_maxrss — the lifetime
    PEAK, in KB on Linux but bytes on Darwin."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        ru = resource.getrusage(resource.RUSAGE_SELF)
        scale = 1 if sys.platform == "darwin" else 1024
        return int(ru.ru_maxrss) * scale


def update_process_gauges() -> dict:
    """Refresh the host-side gauges right before an exposition: process
    RSS (live from /proc, getrusage peak as the fallback), open fd
    count, staging-pool queue depth, and the per-owner live totals."""
    out: dict = {}
    try:
        out["host_rss_bytes"] = _current_rss_bytes()
        counters.set("host_rss_bytes", out["host_rss_bytes"])
    except Exception:
        pass
    try:
        nfds = len(os.listdir("/proc/self/fd"))
        out["host_open_fds"] = nfds
        counters.set("host_open_fds", nfds)
    except OSError:
        pass
    from greengage_tpu.exec import staging as _staging

    depth = _staging.pool_queue_depth()
    out["staging_pool_queue_depth"] = depth
    counters.set("staging_pool_queue_depth", depth)
    for owner, n in owner_live_bytes().items():
        counters.set(f"mem_owner_bytes_{owner}", n)
        out[f"mem_owner_bytes_{owner}"] = n
    return out


# ---- the `gg mem` / {"op": "mem"} report -------------------------------
def report(db=None) -> dict:
    """Everything the operator needs in one frame: live device stats,
    in-flight + recent per-statement accounting trees, the runaway
    tracker's ledger, block-cache budget state, and host gauges."""
    from greengage_tpu.runtime.runaway import TRACKER

    out = {
        "device": device_memory_stats(),
        "process": update_process_gauges(),
        "in_flight": ACCOUNTS.snapshot(),
        "recent": ACCOUNTS.ring(),
        "vmem_tracker": TRACKER.snapshot(),
    }
    if db is not None:
        try:
            out["block_cache"] = db.store.blockcache.stats()
        except Exception:
            pass
        try:
            out["executables"] = executable_mem_summary(db.executor)
        except Exception:
            pass
    return out


def executable_mem_summary(executor) -> list[dict]:
    """Per cached executable: the statement key, compile-time estimate,
    and measured memory analysis (None until its first dispatch)."""
    out = []
    for key, comp in list(executor._plan_cache.items()):
        out.append({
            "statement": str(key[0])[:120],
            "est_bytes": int(getattr(comp, "est_bytes", 0)),
            "measured": getattr(comp, "mem_analysis", None),
        })
    return out
