"""Named fault-point injection registry.

Reference parity: src/backend/utils/misc/faultinjector.c (shmem registry of
named points, types skip/error/sleep/panic/suspend, per-point hit counts)
exposed to SQL via gpcontrib/gp_inject_fault. Ours is a process-local
registry with the same point/type/occurrence model; tests and the FTS/DTM
loops consult it at the same structural spots the reference instruments
(probe send, commit phases, motion send, storage read).

Usage:
    faults.inject("fts_probe", "error", segment=2, occurrences=1)
    ...
    faults.check("fts_probe", segment=2)   # raises FaultError once
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class FaultError(RuntimeError):
    pass


# Registered fault points — the shmem-registry analog's name catalog and
# the source of truth `gg check` (analysis/lint_registry.py) cross-checks:
# every faults.check() site in the package must name a registered point,
# every registered point must have a check() site, and every
# faults.inject() in the test tree must target a registered point (the
# injector's OWN unit tests use throwaway names under a lint pragma).
# Runtime stays permissive — unknown names simply never fire — so the
# registry can't break production; drift is a merge-time lint failure.
FAULT_POINTS = frozenset({
    # multihost control plane (parallel/multihost.py, exec/session.py)
    "dispatch_send", "worker_ack", "heartbeat", "retry_redispatch",
    "mesh_reform", "mirror_promote_during_reform",
    # FTS / DTM (runtime/fts.py, runtime/dtm.py)
    "fts_probe", "dtx_before_prepare", "dtx_after_prepare",
    "dtx_before_commit", "dtx_after_commit", "commit_during_reform",
    # storage read/repair/scrub (storage/)
    "storage_corrupt_block", "repair_copy", "scrub_file", "delta_fold",
    # statement lifecycle (exec/executor.py)
    "cancel_before_dispatch", "cancel_in_staging",
    # memory accounting (exec/executor.py): a 'skip' injection fakes a
    # device RESOURCE_EXHAUSTED at dispatch — OOM classification and
    # spill demotion without a real allocator exhaustion
    "device_oom",
    # vectorized serving (exec/executor.py dispatch_batch): a 'sleep'
    # injection holds a batch on the device so tests can pin window
    # accumulation and stage(k+1)/dispatch(k) pipeline overlap
    "batch_dispatch",
    # overload armor (runtime/server.py, runtime/overload.py): a 'skip'
    # injection at overload_accept forces the connection-shed path as if
    # the server were at max_connections; any firing type at
    # brownout_force is forced memory pressure — the deterministic
    # brownout drill (occurrences=-1 holds the state until reset)
    "overload_accept", "brownout_force",
    # hot-table write path (storage/manifest.py, runtime/ingest.py):
    # intent_stage parks a writer between staging its durable intent and
    # resolving it (kill = in-doubt rollback); intent_resolve fires TWICE
    # per commit — before the merge line is appended and again after it
    # is durable but before the marker unlink — so start_after pins
    # either crash window; ingest_flush parks a stream micro-batch after
    # the buffer is drained and before its intent commit (the mid-stream
    # kill window)
    "intent_stage", "intent_resolve", "ingest_flush",
    # data-movement pipeline (exec/motionpipe.py, exec/workfile.py):
    # motion_bucket fires inside every bucket's stage span — a 'sleep'
    # injection widens stage(k+1) across compute(k) so the overlap test
    # asserts pipelining from span timestamps, not wall-clock luck;
    # spill_capture fires as each spill pass lands in the tiered
    # workfile — an 'error' injection mid-schedule proves the disk tier's
    # segment files are swept by the capture path's finally
    "motion_bucket", "spill_capture",
    # coordinator failover (runtime/standby.py, storage/manifest.py):
    # standby_ship fires at the top of every tail sync — an 'error'
    # injection is a ship failure (lag grows, standby_sync_fail_total
    # counts), a 'sleep' widens the window between a primary commit and
    # its ship; coordinator_fence fires inside the fence check at every
    # manifest commit point — a 'sleep' parks a stale primary's commit
    # across a promotion so the split-brain race is deterministic;
    # standby_promote fires at the head of promote(), before the fence is
    # written — occurrence/start_after targeting pins any crash window in
    # the detect -> fence -> sync -> activate -> recover state machine
    "standby_ship", "coordinator_fence", "standby_promote",
    # self-tuning loop (planner/feedback.py, exec/session.py):
    # feedback_apply fires before a calibration candidate is promoted to
    # an applied scale — 'skip' holds every correction pending (checkperf
    # --apply commits them), 'error' probes the reconcile path's
    # isolation from the statement; runaway_broadcast fires before the
    # coordinator ships the cluster runaway verdict to the gang — 'skip'
    # enforces locally only (partial-failure probe); mh_hbm_watermark
    # fires in the worker's completion-ack watermark read — 'skip'
    # substitutes a synthetic over-limit value so the gang test forces a
    # cluster verdict without a real multi-GB allocation
    "feedback_apply", "runaway_broadcast", "mh_hbm_watermark",
})


@dataclass
class _Fault:
    name: str
    type: str                 # skip | error | sleep | panic | suspend
    segment: int | None       # None = any segment
    occurrences: int          # remaining triggers; -1 = unlimited
    sleep_s: float = 0.0
    start_after: int = 0      # hits to ignore before arming (start_occurrence)
    hits: int = 0


@dataclass
class FaultInjector:
    _faults: dict[str, list[_Fault]] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inject(self, name: str, type: str = "error", segment: int | None = None,
               occurrences: int = 1, sleep_s: float = 0.1,
               start_after: int = 0) -> None:
        """start_after mirrors the reference's start_occurrence: the point
        ignores its first N matching hits before arming, so a test can
        target e.g. the SECOND send of an exchange (the 'go' frame)."""
        if type not in ("skip", "error", "sleep", "panic", "suspend"):
            raise ValueError(f"unknown fault type {type}")
        with self._lock:
            self._faults.setdefault(name, []).append(
                _Fault(name, type, segment, occurrences, sleep_s,
                       start_after))

    def reset(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._faults.clear()
            else:
                self._faults.pop(name, None)

    def check(self, name: str, segment: int | None = None) -> bool:
        """Evaluate a fault point. Returns True if a 'skip' fired (caller
        should skip its action); raises FaultError for 'error'/'panic';
        sleeps for 'sleep'; blocks for 'suspend' until reset."""
        with self._lock:
            entries = self._faults.get(name, [])
            fired = None
            for f in entries:
                if f.segment is not None and segment is not None and f.segment != segment:
                    continue
                if f.occurrences == 0:
                    continue
                if f.start_after > 0:
                    f.start_after -= 1    # not armed yet: let this hit pass
                    continue
                if f.occurrences > 0:
                    f.occurrences -= 1
                f.hits += 1
                fired = f
                break
        if fired is None:
            return False
        if fired.type == "skip":
            return True
        if fired.type == "sleep":
            time.sleep(fired.sleep_s)
            return False
        if fired.type == "suspend":
            while True:
                time.sleep(0.01)
                with self._lock:
                    if fired.name not in self._faults:
                        return False
        raise FaultError(f"fault injected: {name}"
                         + (f" (segment {segment})" if segment is not None else ""))

    def status(self) -> list[dict]:
        with self._lock:
            return [
                {"name": f.name, "type": f.type, "segment": f.segment,
                 "remaining": f.occurrences, "hits": f.hits,
                 "start_after": f.start_after}
                for fs in self._faults.values() for f in fs
            ]


faults = FaultInjector()   # process-global registry (shmem analog)
