"""Structured cluster logging — the elog/syslogger analog.

Reference parity: the CSV server log emitted by the syslogger
(src/backend/postmaster/syslogger.c, write_csvlog in elog.c): one file
per day under ``<cluster>/log/``, one CSV record per event. Field
layout (a condensed version of the reference's 23-column csvlog):

    timestamp, severity, pid, thread, kind, duration_ms, rows, message

Statements, errors, lifecycle events (startup/shutdown/recovery), and
management actions all land here; ``gg logfilter`` (mgmt/cli.py) is the
gplogfilter analog that mines them. Appends are line-atomic under a
process-wide lock; multiple threads (server connections) share one
logger. The logger never raises into the caller — a full disk must not
take the query path down with it.
"""

from __future__ import annotations

import csv
import datetime
import io
import os
import threading

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL", "PANIC")


class Counters:
    """Process-wide monotonic event counters (the pg_stat counter surface):
    storage repair/quarantine/scrub events land here so tests and `gg
    scrub`/`gg state` can assert on behavior without parsing log text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c: dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def set(self, name: str, value: int) -> int:
        """Gauge-style assignment (e.g. mh_topology_version): the counter
        surface also carries a few level values tests assert on."""
        with self._lock:
            self._c[name] = int(value)
            return self._c[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._c)

    def since(self, base: dict[str, int],
              prefix: str | None = None) -> dict[str, int]:
        """Delta vs an earlier snapshot() — the per-statement accounting
        the scan I/O counters (scan_files_read / scan_bytes_decoded /
        scan_cache_*) are read through; deterministic, so tests assert on
        it instead of wall clocks."""
        with self._lock:
            return {k: v - base.get(k, 0) for k, v in self._c.items()
                    if (prefix is None or k.startswith(prefix))
                    and v != base.get(k, 0)}

    def reset(self) -> None:
        with self._lock:
            self._c.clear()


counters = Counters()   # shared registry (shmem stats analog)


class ClusterLog:
    def __init__(self, root: str, enabled: bool = True):
        self.dir = os.path.join(root, "log")
        self.enabled = enabled
        self._lock = threading.Lock()
        self._fh = None            # open append handle for _fh_day
        self._fh_day: datetime.date | None = None

    def _path(self, day: datetime.date | None = None) -> str:
        day = day or datetime.datetime.now(datetime.timezone.utc).date()
        return os.path.join(self.dir, f"ggtpu-{day.isoformat()}.csv")

    def _handle(self):
        """Open (or roll to today's) append handle; called under _lock."""
        day = datetime.datetime.now(datetime.timezone.utc).date()
        if self._fh is None or self._fh_day != day:
            if self._fh is not None:
                self._fh.close()
            os.makedirs(self.dir, exist_ok=True)
            self._fh = open(self._path(day), "a")
            self._fh_day = day
        return self._fh

    def log(self, severity: str, kind: str, message: str,
            duration_ms: float | None = None, rows: int | None = None) -> None:
        if not self.enabled:
            return
        # UTC to match the archive index / recovery_target_time: logfilter
        # timestamps are the natural way to pick a PITR target
        ts = datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="milliseconds").replace("+00:00", "Z")
        buf = io.StringIO()
        csv.writer(buf).writerow([
            ts, severity, os.getpid(), threading.current_thread().name,
            kind, "" if duration_ms is None else f"{duration_ms:.2f}",
            "" if rows is None else rows,
            message.replace("\n", " ")[:500],
        ])
        try:
            with self._lock:
                fh = self._handle()
                fh.write(buf.getvalue())
                fh.flush()   # line-durable for logfilter/crash forensics
        except OSError:
            pass   # logging must never fail the statement

    # convenience levels -------------------------------------------------
    def info(self, kind: str, message: str, **kw) -> None:
        self.log("INFO", kind, message, **kw)

    def error(self, kind: str, message: str, **kw) -> None:
        self.log("ERROR", kind, message, **kw)

    # ---- mining (the gplogfilter core) --------------------------------
    def files(self) -> list[str]:
        if not os.path.isdir(self.dir):
            return []
        return sorted(os.path.join(self.dir, f)
                      for f in os.listdir(self.dir)
                      if f.startswith("ggtpu-") and f.endswith(".csv"))


FIELDS = ("ts", "severity", "pid", "thread", "kind",
          "duration_ms", "rows", "message")


def read_entries(root: str) -> list[dict]:
    """Parse every log file under <root>/log into dicts (FIELDS keys)."""
    out = []
    log = ClusterLog(root)
    for path in log.files():
        with open(path, newline="") as f:
            for rec in csv.reader(f):
                if len(rec) != len(FIELDS):
                    continue   # torn line (crash mid-append)
                out.append(dict(zip(FIELDS, rec)))
    return out


def filter_entries(entries: list[dict], trouble: bool = False,
                   match: str | None = None, begin: str | None = None,
                   end: str | None = None,
                   min_duration_ms: float | None = None) -> list[dict]:
    """gplogfilter semantics: severity gate (-t), regex (-m), time window
    (-b/-e), slow-statement floor."""
    import re

    rx = re.compile(match, re.I) if match else None
    out = []
    for e in entries:
        if trouble and e["severity"] not in ("ERROR", "FATAL", "PANIC"):
            continue
        if rx is not None and not rx.search(e["message"]) \
                and not rx.search(e["kind"]):
            continue
        if begin and e["ts"] < begin:
            continue
        if end and e["ts"] > end:
            continue
        if min_duration_ms is not None:
            try:
                if float(e["duration_ms"] or 0) < min_duration_ms:
                    continue
            except ValueError:
                continue
        out.append(e)
    return out
