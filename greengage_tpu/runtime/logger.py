"""Structured cluster logging — the elog/syslogger analog.

Reference parity: the CSV server log emitted by the syslogger
(src/backend/postmaster/syslogger.c, write_csvlog in elog.c): one file
per day under ``<cluster>/log/``, one CSV record per event. Field
layout (a condensed version of the reference's 23-column csvlog):

    timestamp, severity, pid, thread, kind, duration_ms, rows, message

Statements, errors, lifecycle events (startup/shutdown/recovery), and
management actions all land here; ``gg logfilter`` (mgmt/cli.py) is the
gplogfilter analog that mines them. Appends are line-atomic under a
process-wide lock; multiple threads (server connections) share one
logger. The logger never raises into the caller — a full disk must not
take the query path down with it.
"""

from __future__ import annotations

import bisect
import csv
import datetime
import io
import os
import re
import threading

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL", "PANIC")

# names that are levels, not monotone counts, regardless of how they were
# first written — the Prometheus exposition must emit `# TYPE ... gauge`
# for them even in a process that has only inc()'d so far
GAUGE_NAMES = (
    "mh_topology_version",
    # measured memory accounting (runtime/memaccount.py): live device
    # allocator watermarks (absent on CPU backends — no writer runs),
    # the signed estimate-vs-measured executable error, per-owner live
    # host bytes, and the host process gauges `gg metrics` refreshes
    "device_bytes_in_use", "device_peak_bytes_in_use", "mem_est_error_pct",
    "mem_owner_bytes_staging", "mem_owner_bytes_blockcache",
    "mem_owner_bytes_spill", "mem_owner_bytes_device",
    "host_rss_bytes", "host_open_fds", "staging_pool_queue_depth",
    # vectorized serving (exec/batchserve.py): members waiting in open
    # admission windows right now
    "batch_queue_depth",
    # overload armor (runtime/server.py, runtime/overload.py): live
    # client connections on the serving front end, and whether the
    # memory-pressure brownout is engaged (1) or clear (0)
    "server_active_connections", "brownout",
    # streaming ingest plane (runtime/ingest.py): live stream sessions
    # and rows currently buffered host-side across them
    "ingest_active_streams", "ingest_buffered_rows",
    # tiered spill workfile (exec/workfile.py): bytes currently retained
    # in each tier across all spilling statements — host-RAM captured
    # passes vs compressed disk segments awaiting promotion
    "spill_tier_ram_bytes", "spill_tier_disk_bytes",
    # coordinator failover (runtime/standby.py): committed versions on
    # the primary not yet shipped to the registered standby — 0 while
    # the tail sync keeps up, grows while shipping fails
    "standby_lag_commits",
    # self-tuning loop (planner/feedback.py): generation of the applied
    # calibration — joins the bound-plan cache key, so a bump means every
    # affected shape re-plans; workers track the coordinator's via the
    # dispatch-frame payload
    "calibration_version",
)

# Declared metric catalog — the source of truth `gg check`
# (analysis/lint_registry.py) cross-checks against the package source:
# every counters.inc() site must name a declared counter (f-string
# families match by their literal prefix), every counters.set() site a
# declared gauge, every histograms.observe() site a declared histogram —
# and every declared name must have a writer. Undeclared names are a
# merge-time lint failure, so the docs/OBSERVABILITY.md metric catalog
# and the exposition can't silently drift from the code.
COUNTER_NAMES = (
    # plan / executable cache (exec/session.py, exec/executor.py)
    "plan_cache_hit", "plan_cache_miss", "plan_cache_fallback",
    "program_cache_hit", "program_cache_miss", "program_cache_unsignable",
    "params_hoisted", "compile_ms",
    # statement lifecycle (exec/session.py, runtime/resqueue.py)
    "statements_cancelled_user", "statements_cancelled_timeout",
    "statements_cancelled_runaway", "statements_cancelled_client_gone",
    "statements_cancelled_shutdown", "statements_retried",
    "queue_cancelled_total", "slow_statements",
    # host data path (storage/blockcache.py, exec/executor.py)
    "scan_files_read", "scan_bytes_decoded",
    "scan_cache_hit", "scan_cache_miss", "scan_cache_evict",
    # storage self-heal (storage/table_store.py, storage/scrub.py)
    "storage_repair", "storage_standby_repair", "storage_quarantine",
    "storage_scrub_runs", "storage_scrub_files",
    # manifest commit path + topology (storage/manifest.py, exec/session.py)
    "manifest_delta_commits", "manifest_cas_retry_total",
    "manifest_cas_conflict_total", "manifest_folds", "mh_reform_total",
    # measured memory accounting (exec/executor.py): executable analyses
    # performed (a warm program-cache hit must add ZERO), classified
    # device OOMs, and OOMs absorbed by the one-shot spill demotion
    "mem_analysis_runs", "oom_events", "oom_spill_retries",
    # vectorized serving (exec/batchserve.py): device dispatches vs
    # statements they served (members/dispatch = the amortization
    # factor), why windows flushed, and batches routed back to the
    # serial path (admission ceiling / overflow flags / stage failure)
    "batch_dispatch_total", "batch_members_total",
    "batch_window_flush_full", "batch_window_flush_timer",
    "batch_fallback_total",
    # window engine (planner/planner.py, exec/spill.py): plans kept
    # gather-free (global collective / packed-rank / range-repartition
    # modes) vs plans that still took the one-chip SingleQE funnel, and
    # window-partition spill activity (runs + capture/bucket passes)
    "window_gather_free_total", "window_funnel_total",
    "window_spill_runs", "window_spill_passes",
    # scalar data-path fusion (sql/binder.py, ops/scalar.py): scalar
    # function sites lowered INTO the fused device programs (Func /
    # dictionary LUT / raw byte-window op) vs sites that fell back to the
    # per-row host chain (@hp chain predicates, finalize-decode
    # projections) — the fused-coverage ratio docs/PERF.md tracks
    "scalar_device_total", "scalar_host_fallback_total",
    # overload armor (docs/ROBUSTNESS.md "Overload protection"):
    # connections accepted vs shed at the bounded front end
    # (runtime/server.py), oversized request frames rejected, statements
    # shed at the admission queues (runtime/resqueue.py shed_check),
    # serving-pipeline members shed to the serial path
    # (exec/batchserve.py), and brownout state transitions
    # (runtime/overload.py)
    "server_connections_total", "connections_shed_total",
    "frames_rejected_total", "admission_shed_total",
    "batch_members_shed_total",
    "brownout_entered_total", "brownout_exited_total",
    # hot-table write scale (storage/manifest.py, runtime/ingest.py):
    # write-intent merges resolved into the commit log, state-replacing
    # commits fenced off by a landed merge (clean conflicts), in-doubt /
    # leftover intent markers swept by recovery and grace-GC, and the
    # streaming ingest plane's committed micro-batches, rows, typed
    # sheds, and replayed batches deduplicated on resume
    "manifest_intent_commits", "manifest_intent_conflict_total",
    "manifest_intent_swept_total",
    "ingest_batches_total", "ingest_rows_total", "ingest_shed_total",
    "ingest_resume_dedup_total",
    # data-movement pipeline (exec/motionpipe.py, exec/workfile.py):
    # realized stage(k+1) x compute(k) overlap milliseconds across
    # bucketed schedules, tiered-workfile passes demoted to / promoted
    # from the disk tier, and dead-process spill segments swept at
    # recovery
    "motion_overlap_ms", "spill_demote_total", "spill_promote_total",
    "spill_orphan_sweep_total",
    # coordinator failover (runtime/standby.py, parallel/multihost.py):
    # standby tail-sync ship failures (files the post-commit/watcher sync
    # could NOT ship — the formerly-silent OSError swallow), standby
    # promotions (watcher-automatic or `gg standby --promote`), and
    # workers re-homed to a non-launch coordinator address after
    # CoordinatorLost (the redial walked mh_coordinator_addrs and landed
    # on the promoted standby)
    "standby_sync_fail_total", "standby_promote_total", "mh_rehome_total",
    # self-tuning loop (planner/feedback.py, exec/executor.py):
    # calibration corrections promoted into applied scales, and how each
    # admission verdict was priced — measured footprint (live AOT
    # analysis OR the feedback store's persisted measurement; the
    # _feedback variant counts the persisted subset) vs planner estimate
    "feedback_applied_total",
    "admission_measured_total", "admission_measured_feedback_total",
    "admission_estimated_total",
)

HISTOGRAM_NAMES = (
    "statement_ms", "queue_wait_ms", "compile_latency_ms",
    "stage_ms", "dispatch_ms", "fetch_ms",
    # measured executable footprint (args+temps+output, MB buckets —
    # observed with DEFAULT_BUCKETS_MB, not the ms defaults)
    "executable_mem_mb",
    # vectorized serving: members per flushed batch (pow2-width buckets,
    # exec/batchserve.WIDTH_BUCKETS — not the ms defaults)
    "batch_width",
)


class Counters:
    """Process-wide monotonic event counters (the pg_stat counter surface):
    storage repair/quarantine/scrub events land here so tests and `gg
    scrub`/`gg state` can assert on behavior without parsing log text.
    Names written through set() are tagged as GAUGES (levels, e.g.
    mh_topology_version) so the Prometheus exposition types them right."""

    def __init__(self):
        from greengage_tpu.runtime import lockdebug

        self._lock = lockdebug.named(threading.Lock(),
                                     "logger.counters._lock")
        # access-witnessed under GGTPU_RACE_DEBUG: every touch must hold
        # the counters lock (docs/ANALYSIS.md "Race analysis")
        self._c: dict[str, int] = lockdebug.shared({}, "logger.counters._c")
        self._gauges: set[str] = set(GAUGE_NAMES)

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def set(self, name: str, value: int) -> int:
        """Gauge-style assignment (e.g. mh_topology_version): the counter
        surface also carries a few level values tests assert on."""
        with self._lock:
            self._c[name] = int(value)
            self._gauges.add(name)
            return self._c[name]

    def gauges(self) -> set[str]:
        """Names holding gauge (level) semantics; everything else in
        snapshot() is a monotone counter."""
        with self._lock:
            return set(self._gauges)

    def kind(self, name: str) -> str:
        with self._lock:
            return "gauge" if name in self._gauges else "counter"

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            # items() not dict(): one access-witness record per snapshot
            # instead of one per key (GGTPU_RACE_DEBUG)
            return dict(self._c.items())

    def since(self, base: dict[str, int],
              prefix: str | None = None) -> dict[str, int]:
        """Delta vs an earlier snapshot() — the per-statement accounting
        the scan I/O counters (scan_files_read / scan_bytes_decoded /
        scan_cache_*) are read through; deterministic, so tests assert on
        it instead of wall clocks."""
        with self._lock:
            return {k: v - base.get(k, 0) for k, v in self._c.items()
                    if (prefix is None or k.startswith(prefix))
                    and v != base.get(k, 0)}

    def reset(self) -> None:
        with self._lock:
            self._c.clear()


counters = Counters()   # shared registry (shmem stats analog)


# fixed latency buckets (ms): wide enough for a cold XLA compile, fine
# enough for a warm cached statement — fixed so two processes' expositions
# aggregate bucket-by-bucket in Prometheus
DEFAULT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)

# byte-sized histograms (executable memory footprints) bucket in MB:
# fine enough for point-query programs, wide enough for a v5e's 16 GB
DEFAULT_BUCKETS_MB = (1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0,
                      16384.0)


class Histograms:
    """Fixed-bucket latency histograms (the pg_stat_statements timing
    role, shaped for Prometheus exposition): statement latency, host
    data-path phases, queue waits. observe() is O(log buckets) under one
    lock — safe for every statement."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> [buckets tuple, per-bucket counts, overflow, sum, count]
        self._h: dict[str, list] = {}

    def observe(self, name: str, value_ms: float,
                buckets: tuple = DEFAULT_BUCKETS_MS) -> None:
        v = float(value_ms)
        with self._lock:
            h = self._h.get(name)
            if h is None:
                h = self._h[name] = [tuple(buckets),
                                     [0] * len(buckets), 0, 0.0, 0]
            bks, counts, _over, _s, _n = h
            i = bisect.bisect_left(bks, v)
            if i < len(bks):
                counts[i] += 1
            else:
                h[2] += 1
            h[3] += v
            h[4] += 1

    def snapshot(self) -> dict:
        """name -> {"buckets": [...], "counts": [...per bucket...],
        "sum": total_ms, "count": n}; counts are per-bucket (NOT
        cumulative) — the exposition cumulates."""
        with self._lock:
            return {name: {"buckets": list(h[0]), "counts": list(h[1]),
                           "sum": h[3], "count": h[4]}
                    for name, h in self._h.items()}

    def reset(self) -> None:
        with self._lock:
            self._h.clear()


histograms = Histograms()   # shared registry, same lifetime as `counters`


_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str, prefix: str) -> str:
    s = _METRIC_NAME_RE.sub("_", name)
    if s and s[0].isdigit():
        s = "_" + s
    return prefix + s


def _fmt_float(v: float) -> str:
    return repr(round(float(v), 6))


def prometheus_text(prefix: str = "ggtpu_") -> str:
    """Prometheus text exposition (format 0.0.4) over the process-wide
    counters, gauges, and histograms — the `gg metrics` / server
    {"op":"metrics"} payload. Counter vs gauge typing comes from the
    Counters gauge tags (set() marks a name as a gauge)."""
    lines: list[str] = []
    snap = counters.snapshot()
    gauges = counters.gauges()
    for name in sorted(snap):
        mn = _metric_name(name, prefix)
        lines.append(f"# TYPE {mn} {'gauge' if name in gauges else 'counter'}")
        lines.append(f"{mn} {snap[name]}")
    hsnap = histograms.snapshot()
    counter_names = {_metric_name(n, prefix) for n in snap}
    for name in sorted(hsnap):
        h = hsnap[name]
        mn = _metric_name(name, prefix)
        if mn in counter_names:
            # one exposition name cannot carry two TYPEs: a histogram
            # colliding with a counter/gauge family exports suffixed
            mn += "_hist"
        lines.append(f"# TYPE {mn} histogram")
        cum = 0
        for b, c in zip(h["buckets"], h["counts"]):
            cum += c
            lines.append(f'{mn}_bucket{{le="{b:g}"}} {cum}')
        lines.append(f'{mn}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{mn}_sum {_fmt_float(h['sum'])}")
        lines.append(f"{mn}_count {h['count']}")
    return "\n".join(lines) + "\n"


class ClusterLog:
    def __init__(self, root: str, enabled: bool = True):
        self.dir = os.path.join(root, "log")
        self.enabled = enabled
        self._lock = threading.Lock()
        self._fh = None            # open append handle for _fh_day
        self._fh_day: datetime.date | None = None

    def _path(self, day: datetime.date | None = None) -> str:
        day = day or datetime.datetime.now(datetime.timezone.utc).date()
        return os.path.join(self.dir, f"ggtpu-{day.isoformat()}.csv")

    def _handle(self):
        """Open (or roll to today's) append handle; called under _lock."""
        day = datetime.datetime.now(datetime.timezone.utc).date()
        if self._fh is None or self._fh_day != day:
            if self._fh is not None:
                self._fh.close()
            os.makedirs(self.dir, exist_ok=True)
            self._fh = open(self._path(day), "a")
            self._fh_day = day
        return self._fh

    def log(self, severity: str, kind: str, message: str,
            duration_ms: float | None = None, rows: int | None = None) -> None:
        if not self.enabled:
            return
        # UTC to match the archive index / recovery_target_time: logfilter
        # timestamps are the natural way to pick a PITR target
        ts = datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="milliseconds").replace("+00:00", "Z")
        buf = io.StringIO()
        csv.writer(buf).writerow([
            ts, severity, os.getpid(), threading.current_thread().name,
            kind, "" if duration_ms is None else f"{duration_ms:.2f}",
            "" if rows is None else rows,
            message.replace("\n", " ")[:500],
        ])
        try:
            with self._lock:
                fh = self._handle()
                fh.write(buf.getvalue())
                fh.flush()   # line-durable for logfilter/crash forensics
        except OSError:
            pass   # logging must never fail the statement

    # convenience levels -------------------------------------------------
    def info(self, kind: str, message: str, **kw) -> None:
        self.log("INFO", kind, message, **kw)

    def error(self, kind: str, message: str, **kw) -> None:
        self.log("ERROR", kind, message, **kw)

    # ---- mining (the gplogfilter core) --------------------------------
    def files(self) -> list[str]:
        if not os.path.isdir(self.dir):
            return []
        return sorted(os.path.join(self.dir, f)
                      for f in os.listdir(self.dir)
                      if f.startswith("ggtpu-") and f.endswith(".csv"))


FIELDS = ("ts", "severity", "pid", "thread", "kind",
          "duration_ms", "rows", "message")


def read_entries(root: str) -> list[dict]:
    """Parse every log file under <root>/log into dicts (FIELDS keys)."""
    out = []
    log = ClusterLog(root)
    for path in log.files():
        with open(path, newline="") as f:
            for rec in csv.reader(f):
                if len(rec) != len(FIELDS):
                    continue   # torn line (crash mid-append)
                out.append(dict(zip(FIELDS, rec)))
    return out


def filter_entries(entries: list[dict], trouble: bool = False,
                   match: str | None = None, begin: str | None = None,
                   end: str | None = None,
                   min_duration_ms: float | None = None) -> list[dict]:
    """gplogfilter semantics: severity gate (-t), regex (-m), time window
    (-b/-e), slow-statement floor."""
    rx = re.compile(match, re.I) if match else None
    out = []
    for e in entries:
        if trouble and e["severity"] not in ("ERROR", "FATAL", "PANIC"):
            continue
        if rx is not None and not rx.search(e["message"]) \
                and not rx.search(e["kind"]):
            continue
        if begin and e["ts"] < begin:
            continue
        if end and e["ts"] > end:
            continue
        if min_duration_ms is not None:
            try:
                if float(e["duration_ms"] or 0) < min_duration_ms:
                    continue
            except ValueError:
                continue
        out.append(e)
    return out
