"""S3 object-store external protocol — the gpcontrib/gpcloud analog
(reference: gpcontrib/gpcloud/src/, ~11k LoC of C++ around libcurl +
SigV4), redesigned as a slim pure-python client: on a TPU pod the object
store is the PRIMARY ingest path, so s3:// is a first-class external
LOCATION protocol next to file:// and gpfdist://.

URL syntax (gpcloud-compatible):
    s3://<endpoint>/<bucket>/<prefix> [config=<path>] [region=<r>]
e.g.  s3://s3-us-west-2.amazonaws.com/mybucket/tpch/lineitem
      s3://127.0.0.1:9000/test/data config=/etc/s3.conf

Config file (s3.conf, gpcloud's [default] ini shape):
    [default]
    accessid = AKID...
    secret = ...
    region = us-east-1
    https = false          # plain http for private stores / mocks

Requests are path-style; authentication is AWS Signature V4 implemented
directly (HMAC-SHA256 canonical request -> string-to-sign -> signing
key), pinned by the published AWS test vector in tests. Without
credentials, requests go unsigned (public buckets / anonymous stores).
Reads list the prefix via ListObjectsV2 (continuation-token pagination)
and GET every object; writable external tables PUT one object per
INSERT batch.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
import configparser


class S3Error(IOError):
    pass


def parse_s3_url(url: str) -> tuple[str, str, str, dict]:
    """-> (endpoint host[:port], bucket, prefix, opts) from an s3:// URL
    with optional space-separated key=value options."""
    if not url.startswith("s3://"):
        raise S3Error(f"not an s3 URL: {url!r}")
    body, *optparts = url[len("s3://"):].split()
    opts = {}
    for p in optparts:
        if "=" not in p:
            raise S3Error(f"malformed s3 option {p!r} (want key=value)")
        k, v = p.split("=", 1)
        opts[k.strip()] = v.strip()
    pieces = body.split("/", 2)
    if len(pieces) < 2 or not pieces[0] or not pieces[1]:
        raise S3Error(f"s3 URL needs s3://endpoint/bucket[/prefix]: {url!r}")
    endpoint, bucket = pieces[0], pieces[1]
    prefix = pieces[2] if len(pieces) > 2 else ""
    return endpoint, bucket, prefix, opts


def load_config(path: str | None) -> dict:
    """gpcloud s3.conf ([default] ini): accessid/secret/region/https."""
    conf = {"accessid": "", "secret": "", "region": "us-east-1",
            "https": True}
    if not path:
        return conf

    cp = configparser.ConfigParser()
    read = cp.read(path)
    if not read:
        raise S3Error(f"cannot read s3 config {path!r}")
    sec = cp["default"] if "default" in cp else cp[cp.sections()[0]]
    conf["accessid"] = sec.get("accessid", "")
    conf["secret"] = sec.get("secret", "")
    conf["region"] = sec.get("region", "us-east-1")
    conf["https"] = sec.getboolean("https", fallback=True)
    return conf


# ---------------------------------------------------------------------------
# AWS Signature Version 4
# ---------------------------------------------------------------------------

def _sha256(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _quote(s: str) -> str:
    return urllib.parse.quote(s, safe="-_.~")


def sigv4_headers(method: str, host: str, uri: str, query: dict,
                  payload: bytes, accessid: str, secret: str, region: str,
                  service: str = "s3", now: datetime.datetime | None = None,
                  extra_headers: dict | None = None,
                  sign_payload_header: bool = True) -> dict:
    """Sign one request: -> headers incl. Authorization, x-amz-date, and
    (for S3) x-amz-content-sha256 — the canonical-request ->
    string-to-sign -> signing-key pipeline of the SigV4 spec, pinned
    against the published AWS iam/ListUsers vector in tests/test_s3.py
    (that example signs WITHOUT the S3-only payload header)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amzdate = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = _sha256(payload)
    headers = {"host": host, "x-amz-date": amzdate}
    if sign_payload_header:
        headers["x-amz-content-sha256"] = payload_hash
    for k, v in (extra_headers or {}).items():
        headers[k.lower()] = v
    canonical_uri = urllib.parse.quote(uri, safe="/-_.~")
    canonical_query = "&".join(
        f"{_quote(k)}={_quote(str(v))}" for k, v in sorted(query.items()))
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(
        f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
    creq = "\n".join([method, canonical_uri, canonical_query,
                      canonical_headers, signed, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope, _sha256(creq.encode())])
    k = _hmac(("AWS4" + secret).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={accessid}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    return headers


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

def _request(method: str, endpoint: str, uri: str, query: dict,
             payload: bytes, conf: dict, timeout: float = 60.0) -> bytes:
    scheme = "https" if conf.get("https", True) else "http"
    # the SENT query string must byte-match the SIGNED canonical query
    # (urlencode's '+' for space differs from SigV4's %20)
    qs = "&".join(f"{_quote(k)}={_quote(str(v))}"
                  for k, v in sorted(query.items()))
    url = f"{scheme}://{endpoint}{urllib.parse.quote(uri, safe='/-_.~')}" \
          + (f"?{qs}" if qs else "")
    req = urllib.request.Request(url, data=payload or None, method=method)
    if conf.get("accessid") and conf.get("secret"):
        host = endpoint
        hdrs = sigv4_headers(method, host, uri, query, payload or b"",
                             conf["accessid"], conf["secret"],
                             conf.get("region", "us-east-1"))
        for k, v in hdrs.items():
            if k != "host":
                req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        raise S3Error(f"s3 {method} {uri} failed: HTTP {e.code} "
                      f"{e.read()[:200]!r}")
    except urllib.error.URLError as e:
        raise S3Error(f"s3 endpoint unreachable: {e.reason}")


def list_objects(endpoint: str, bucket: str, prefix: str,
                 conf: dict) -> list[str]:
    """ListObjectsV2 with continuation-token pagination -> sorted keys."""
    keys: list[str] = []
    token = None
    while True:
        q = {"list-type": "2", "prefix": prefix}
        if token:
            q["continuation-token"] = token
        body = _request("GET", endpoint, f"/{bucket}", q, b"", conf)
        root = ET.fromstring(body)
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        for c in root.findall(f"{ns}Contents"):
            k = c.find(f"{ns}Key")
            if k is not None and k.text:
                keys.append(k.text)
        trunc = root.find(f"{ns}IsTruncated")
        token_el = root.find(f"{ns}NextContinuationToken")
        if trunc is not None and trunc.text == "true" \
                and token_el is not None and token_el.text:
            token = token_el.text
            continue
        break
    return sorted(keys)


def get_object(endpoint: str, bucket: str, key: str, conf: dict) -> bytes:
    return _request("GET", endpoint, f"/{bucket}/{key}", {}, b"", conf)


def put_object(endpoint: str, bucket: str, key: str, data: bytes,
               conf: dict) -> None:
    _request("PUT", endpoint, f"/{bucket}/{key}", {}, data, conf)


# ---------------------------------------------------------------------------
# external-table entry points
# ---------------------------------------------------------------------------

def _conf_for(url: str) -> tuple[str, str, str, dict]:
    endpoint, bucket, prefix, opts = parse_s3_url(url)
    conf = load_config(opts.get("config"))
    if "region" in opts:
        conf["region"] = opts["region"]
    # private stores / mocks are plain http; detect a :port endpoint
    # without config as http unless told otherwise
    if "config" not in opts and ":" in endpoint:
        conf["https"] = False
    return endpoint, bucket, prefix, conf


def fetch(url: str) -> list[tuple[str, bytes]]:
    """Read path: every object under the prefix -> (key, bytes), one
    external 'file' per object (HEADER semantics apply per object)."""
    endpoint, bucket, prefix, conf = _conf_for(url)
    out = []
    for key in list_objects(endpoint, bucket, prefix, conf):
        out.append((key, get_object(endpoint, bucket, key, conf)))
    return out


def store(url: str, name: str, data: bytes) -> str:
    """Write path: PUT one object under the prefix. -> object key."""
    endpoint, bucket, prefix, conf = _conf_for(url)
    key = f"{prefix.rstrip('/')}/{name}" if prefix else name
    put_object(endpoint, bucket, key, data, conf)
    return key
