"""Shared retry/deadline policy for every bounded-time loop in the engine.

Reference parity: the reference scatters retry logic across the FTS probe
FSM (ftsprobe.c restart/backoff), libpq connect retries in cdbgang.c, and
dispatcher wait timeouts (poll() with gp_segment_connect_timeout).  Ours
centralizes the three primitives they all share:

  * ``Deadline``    — a monotonic budget that can be split across steps
                      (connect, handshake, per-ack reads) without drifting,
  * ``backoff_delays`` — exponential backoff with full jitter (the
                      AWS-style decorrelated sleep that avoids thundering
                      herds when a whole gang reconnects at once),
  * ``RetryPolicy`` — retry-a-callable with retryable-error classification,
                      bounded by attempts and/or a deadline.

This module is intentionally stdlib-only: ``bench.py`` loads it by file
path from outside the package (the bench parent must never import jax),
and the control channel uses it before any device runtime exists.
"""

from __future__ import annotations

import random
import socket
import sys
import time


def _check_interrupts() -> None:
    """Interrupt poll that keeps this module stdlib-only: when the engine
    is loaded, retry sleeps are statement cancellation points (PR-4
    discipline); when bench.py file-loads this module standalone, the
    registry module is absent and this is a no-op."""
    mod = sys.modules.get("greengage_tpu.runtime.interrupt")
    if mod is not None:
        mod.check_interrupts()

# Errors that indicate a transient transport condition: the peer is not
# (yet) reachable or the exchange timed out — retrying can succeed.
# Anything else (protocol garbage, programming errors) must propagate.
TRANSIENT_ERRORS = (
    ConnectionError,          # refused / reset / aborted / broken pipe
    socket.timeout,           # alias of TimeoutError on 3.10+, kept explicit
    TimeoutError,
    InterruptedError,
    socket.gaierror,          # transient resolver failure on reconnect
)


class Deadline:
    """A monotonic time budget. ``Deadline(None)`` never expires."""

    __slots__ = ("seconds", "_end")

    def __init__(self, seconds: float | None):
        self.seconds = seconds
        self._end = None if seconds is None else time.monotonic() + seconds

    @classmethod
    def after(cls, seconds: float | None) -> "Deadline":
        return cls(seconds)

    @property
    def expired(self) -> bool:
        return self._end is not None and time.monotonic() >= self._end

    def remaining(self, minimum: float = 0.0) -> float | None:
        """Seconds left (>= minimum), or None for an unbounded deadline."""
        if self._end is None:
            return None
        return max(minimum, self._end - time.monotonic())

    def clamp(self, seconds: float) -> float:
        """Bound a step's own timeout by what's left of the budget."""
        rem = self.remaining()
        return seconds if rem is None else min(seconds, rem)

    def require(self, what: str) -> None:
        """Raise TimeoutError if the budget is spent (named for the log)."""
        if self.expired:
            raise TimeoutError(
                f"{what} exceeded the {self.seconds:.1f}s deadline")


def backoff_delays(base: float = 0.1, factor: float = 2.0, cap: float = 30.0,
                   jitter: float = 0.5, deadline: Deadline | None = None):
    """Yield exponentially growing sleep lengths with full jitter.

    Each delay is drawn uniformly from
    ``[d * (1 - jitter), d * (1 + jitter)]`` where ``d`` doubles (by
    ``factor``) from ``base`` up to ``cap``.  With a ``deadline``, delays
    are clamped to the remaining budget and the generator stops once the
    budget is spent (so callers can ``for delay in ...: sleep(delay)``).
    """
    d = base
    while True:
        if deadline is not None and deadline.expired:
            return
        lo, hi = d * (1.0 - jitter), d * (1.0 + jitter)
        delay = random.uniform(max(0.0, lo), hi)
        if deadline is not None:
            delay = deadline.clamp(delay)
        yield delay
        d = min(d * factor, cap)


class RetryPolicy:
    """Retry a callable on transient errors, bounded by attempts and/or a
    deadline.  The last error propagates when the budget is spent."""

    def __init__(self, deadline_s: float | None = None,
                 attempts: int | None = None, base_s: float = 0.1,
                 factor: float = 2.0, cap_s: float = 5.0,
                 jitter: float = 0.5, retryable: tuple = TRANSIENT_ERRORS):
        if deadline_s is None and attempts is None:
            raise ValueError("RetryPolicy needs a deadline and/or attempts")
        self.deadline_s = deadline_s
        self.attempts = attempts
        self.base_s = base_s
        self.factor = factor
        self.cap_s = cap_s
        self.jitter = jitter
        self.retryable = retryable

    def call(self, fn, on_retry=None):
        deadline = Deadline(self.deadline_s)
        delays = backoff_delays(self.base_s, self.factor, self.cap_s,
                                self.jitter, deadline)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except self.retryable as e:
                out_of_attempts = (self.attempts is not None
                                   and attempt >= self.attempts)
                delay = None if out_of_attempts else next(delays, None)
                if delay is None:      # budget spent (attempts or deadline)
                    raise
                if on_retry is not None:
                    try:
                        on_retry(attempt, e, delay)
                    except Exception:
                        pass
                # backoff in short slices so a cancel LANDING mid-sleep
                # fires within ~0.25s, not after the full delay (cap_s=5)
                until = time.monotonic() + delay
                while True:
                    _check_interrupts()
                    rem = until - time.monotonic()
                    if rem <= 0:
                        break
                    time.sleep(min(rem, 0.25))
