"""Per-statement interrupts — the CHECK_FOR_INTERRUPTS analog.

Reference parity: every reference backend polls CHECK_FOR_INTERRUPTS
(src/include/miscadmin.h, ProcessInterrupts in tcop/postgres.c) so a
statement can be cancelled wherever it happens to be blocked — and
enforcement (statement_timeout), operator action (pg_cancel_backend), the
runaway cleaner, and client disconnects all converge on the SAME flag.

The XLA translation: a dispatched device program cannot be preempted, so
cancellation is BOUNDARY-GRANULAR — the flag is polled at every place a
statement can linger on the host:

  * executor retry-tier boundaries (before each compile/dispatch attempt),
  * right before device dispatch (after staging),
  * staging-pool read units (a multi-second cold stage dies mid-flight),
  * spill pass / merge-bucket boundaries,
  * ``ResourceQueue.admit()`` waits (a queued statement leaves the queue),
  * multihost ack-collection loops (per-worker read boundaries).

One ``StatementContext`` is registered per executing statement in the
process-wide ``REGISTRY`` (keyed by thread — one server connection is one
thread, like one backend per libpq connection). It carries a cancel flag
with a typed cause: ``user`` (gg cancel / the cancel protocol frame),
``timeout`` (statement_timeout_s), ``runaway`` (the vmem red-zone
cleaner), ``client_gone`` (connection dropped), ``shutdown`` (server
stopping). ``check()`` raises ``StatementCancelled`` and the session
counts it once in the ``statements_cancelled_<cause>`` counter family.

Nested executor runs (spill passes, recursive-CTE iterations) share the
outermost statement's context, keeping the whole statement one
cancellable unit — the same discipline runtime/runaway.py uses.
"""

from __future__ import annotations

import itertools
import threading
import time

CAUSES = ("user", "timeout", "runaway", "client_gone", "shutdown")


class StatementCancelled(RuntimeError):
    """Raised at a cancellation point after the statement's flag was set
    (or its deadline expired). ``cause`` is one of CAUSES."""

    def __init__(self, message: str, cause: str = "user"):
        super().__init__(message)
        self.cause = cause


class StatementContext:
    """One executing statement's interrupt state. Thread-safe: cancel()
    may be called from any thread (server control connection, runaway
    cleaner, heartbeat); check() runs on the statement's thread AND on
    staging-pool worker threads acting for it."""

    __slots__ = ("statement_id", "sql", "thread", "started",
                 "deadline", "_lock", "_cause", "_message", "_listeners",
                 "counted", "depth")

    def __init__(self, statement_id: int, sql: str,
                 timeout_s: float = 0.0):
        self.statement_id = statement_id
        self.sql = sql
        self.thread = threading.get_ident()
        self.started = time.monotonic()
        # statement_timeout_s arms a deadline at statement start; 0 = off
        self.deadline = (self.started + timeout_s) if timeout_s > 0 else None
        self._lock = threading.Lock()
        self._cause: str | None = None
        self._message: str | None = None
        self._listeners: list = []
        self.counted = False   # session-level once-per-statement counting
        self.depth = 1         # nested runs share the outermost context

    # ---- cancellation ------------------------------------------------
    def cancel(self, cause: str, message: str | None = None) -> None:
        """Set the flag (first cause wins) and wake registered waiters
        (e.g. a resource-queue wait). Never raises into the caller."""
        if cause not in CAUSES:
            cause = "user"
        with self._lock:
            if self._cause is not None:
                return
            self._cause = cause
            self._message = message
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb()
            except Exception:
                pass

    @property
    def cancelled(self) -> bool:
        return self._cause is not None or (
            self.deadline is not None and time.monotonic() >= self.deadline)

    @property
    def cause(self) -> str | None:
        return self._cause

    def remaining(self) -> float | None:
        """Seconds until the statement deadline (None = no timeout)."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    def check(self) -> None:
        """Cancellation point: raise StatementCancelled if flagged or past
        the statement deadline (trips the flag with cause 'timeout')."""
        if self._cause is None and self.deadline is not None \
                and time.monotonic() >= self.deadline:
            self.cancel("timeout",
                        f"canceling statement due to statement timeout "
                        f"(statement_timeout_s = "
                        f"{self.deadline - self.started:.3g})")
        cause = self._cause
        if cause is None:
            return
        msg = self._message or {
            "user": "canceling statement due to user request",
            "timeout": "canceling statement due to statement timeout",
            "client_gone": "canceling statement: client connection lost",
            "shutdown": "canceling statement due to server shutdown",
            "runaway": "canceled by the runaway cleaner",
        }.get(cause, "statement cancelled")
        if cause == "runaway":
            # typed subclass so clients distinguish a runaway kill (their
            # statement held too much HBM) from a plain cancel; deferred
            # import — runaway.py imports this module at load
            from greengage_tpu.runtime.runaway import RunawayCancelled

            raise RunawayCancelled(msg)
        raise StatementCancelled(msg, cause)

    # ---- wait integration (resource queue etc.) ----------------------
    def add_listener(self, cb) -> None:
        """Register a wakeup callback fired once at cancel(); if the flag
        is ALREADY set, fire immediately (no lost wakeup)."""
        with self._lock:
            if self._cause is None:
                self._listeners.append(cb)
                return
        try:
            cb()
        except Exception:
            pass

    def remove_listener(self, cb) -> None:
        with self._lock:
            try:
                self._listeners.remove(cb)
            except ValueError:
                pass

    # ---- observability (pg_stat_activity row) ------------------------
    def describe(self) -> dict:
        return {
            "id": self.statement_id,
            "sql": (self.sql or "").strip()[:200],
            "elapsed_s": round(time.monotonic() - self.started, 3),
            "thread": self.thread,
            "cancelled": self._cause,
            "timeout_in_s": (None if self.deadline is None
                             else round(self.deadline - time.monotonic(), 3)),
        }


class StatementRegistry:
    """Process-wide registry of in-flight statements — the
    pg_stat_activity / pg_cancel_backend surface. One entry per executing
    thread; statement ids are monotonic per process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._by_thread: dict[int, StatementContext] = {}

    def enter(self, sql: str, timeout_s: float = 0.0):
        """Register the calling thread's statement. Nested calls (spill
        passes, recursive-CTE fixpoints, retry redispatch) re-enter the
        existing context. -> (ctx, is_outermost)."""
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is not None:
                cur.depth += 1
                return cur, False
            ctx = StatementContext(next(self._ids), sql, timeout_s)
            self._by_thread[tid] = ctx
            return ctx, True

    def exit(self, ctx: StatementContext) -> None:
        tid = threading.get_ident()
        with self._lock:
            cur = self._by_thread.get(tid)
            if cur is None:
                return
            cur.depth -= 1
            if cur.depth <= 0:
                del self._by_thread[tid]

    def current(self) -> StatementContext | None:
        # deliberately lock-free: the calling thread reads ITS OWN entry,
        # which only this same thread inserts/deletes (enter/exit), and
        # this runs at every CHECK_FOR_INTERRUPTS — a mutex here would
        # tax every cancellation point in the engine
        return self._by_thread.get(threading.get_ident())   # gg:ok(races)

    def get(self, statement_id: int) -> StatementContext | None:
        with self._lock:
            for ctx in self._by_thread.values():
                if ctx.statement_id == statement_id:
                    return ctx
        return None

    def cancel(self, statement_id: int, cause: str = "user",
               message: str | None = None) -> bool:
        """pg_cancel_backend: flag one statement by id. False when no
        such statement is in flight."""
        ctx = self.get(statement_id)
        if ctx is None:
            return False
        ctx.cancel(cause, message)
        return True

    def cancel_thread(self, thread_ident: int, cause: str,
                      message: str | None = None) -> bool:
        """Cancel whatever statement ``thread_ident`` is running (the
        server's client_gone path)."""
        with self._lock:
            ctx = self._by_thread.get(thread_ident)
        if ctx is None:
            return False
        ctx.cancel(cause, message)
        return True

    def cancel_all(self, cause: str, message: str | None = None) -> int:
        """Flag every in-flight statement (server shutdown)."""
        with self._lock:
            ctxs = list(self._by_thread.values())
        for ctx in ctxs:
            ctx.cancel(cause, message)
        return len(ctxs)

    def snapshot(self) -> list[dict]:
        """pg_stat_activity rows for `gg ps`, sorted oldest first."""
        with self._lock:
            ctxs = list(self._by_thread.values())
        return sorted((c.describe() for c in ctxs), key=lambda d: d["id"])


REGISTRY = StatementRegistry()   # shmem PGPROC-array analog


def check_interrupts() -> None:
    """Module-level CHECK_FOR_INTERRUPTS: a no-op for threads with no
    registered statement (worker loops, heartbeats, prefetchers)."""
    ctx = REGISTRY.current()
    if ctx is not None:
        ctx.check()
