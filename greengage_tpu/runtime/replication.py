"""Physical segment replication — the gp_replication.c / walsender analog.

The reference streams WAL from every primary to its mirror and gates commit
on sync acknowledgement (src/backend/replication/gp_replication.c,
syncrep.c); FTS only promotes an in-sync mirror. Our storage is append-only
with immutable committed files and the manifest as the single commit
record, so replication reduces to: after each commit, copy any manifest-
referenced segment files of content k from the ACTING primary's tree to
the standby tree, then durably record the replicated manifest version in
the standby tree. "In sync" = the standby's recorded version == current
manifest version — the WAL-flush-LSN comparison FTS does via
gp_stat_replication.

Each content has two directory trees (different disks/hosts in a real
deployment):

    primary tree:  <root>/data/<table>/seg<k>/...
    mirror tree:   <root>/mirror/content<k>/<table>/seg<k>/...

Which tree is ACTING is decided by SegmentConfig roles (a promoted mirror
acts from the mirror tree; TableStore.data_root resolves every read/write
through it), so replication is direction-agnostic: it always copies
acting -> standby. After a failover, committed writes land in the mirror
tree and flow back to the original primary's tree on the next sync — the
original primary is only promotable again once its tree has caught up.
Rebuild (gprecoverseg full recovery, buildMirrorSegments.py:85) is the same
copy run to completion for a tree that lost files.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile

from greengage_tpu.catalog.segments import SegmentRole, SegmentStatus
from greengage_tpu.storage.blockfile import fsync_dir
from greengage_tpu.storage.table_store import mirror_root


def copy_durable(src: str, dst: str, tmp: str | None = None) -> None:
    """Copy src -> dst with the data fsynced BEFORE the atomic rename and
    the containing directory fsynced after. The repair path and the FTS
    sync-state check both TRUST files under a synced marker, so a crash
    must never leave torn mirror bytes behind a marker that says synced.
    ``tmp`` overrides the staging name (repair passes a unique one so
    concurrent repairers never interleave writes)."""
    tmp = tmp or dst + ".tmp"
    with open(src, "rb") as s, open(tmp, "wb") as d:
        shutil.copyfileobj(s, d)
        d.flush()
        os.fsync(d.fileno())
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(dst))


def _tree_root(store_root: str, content: int, preferred_role) -> str:
    """The directory tree a segment entry's files live in (fixed by its
    PREFERRED role — promotion changes who acts, not where files live)."""
    if preferred_role is SegmentRole.MIRROR:
        return mirror_root(store_root, content)
    return os.path.join(store_root, "data")


def _marker_path(tree: str, content: int) -> str:
    return os.path.join(tree, f".synced_content{content}")


def tree_version(tree: str, content: int) -> int:
    """Manifest version this tree has fully replicated (-1 = never)."""
    try:
        with open(_marker_path(tree, content)) as f:
            return json.load(f)["version"]
    except (OSError, ValueError, KeyError):
        return -1


def replicated_version(store_root: str, content: int) -> int:
    """Version replicated to the MIRROR tree (convenience for tests)."""
    return tree_version(mirror_root(store_root, content), content)


def _write_marker(tree: str, content: int, version: int) -> None:
    os.makedirs(tree, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=tree, prefix=".synced")
    with os.fdopen(fd, "w") as f:
        json.dump({"version": version}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, _marker_path(tree, content))
    fsync_dir(tree)


class Replicator:
    """Post-commit acting->standby file-copy replication per content."""

    def __init__(self, store, config):
        self.store = store
        self.config = config

    def _pairs(self):
        """-> [(content, standby entry)] for every mirrored content."""
        out = []
        for e in self.config.entries:
            if e.content >= 0 and e.role is SegmentRole.MIRROR:
                out.append((e.content, e))
        return sorted(out, key=lambda p: p[0])

    def _copy_content(self, snap: dict, content: int,
                      dst_tree: str) -> tuple[int, int]:
        """Copy every manifest-referenced file + dictionaries of this
        content from the acting tree into dst_tree. Committed files are
        immutable, so copy-if-absent is a complete incremental protocol.
        -> (copied, missing): a quarantined/lost source is SKIPPED, not an
        error — FTS and the scrubber own that failure, and one content's
        corruption must not fail unrelated statements' post-commit sync —
        but the caller must not mark a tree with missing files synced."""
        src_tree = self.store.data_root(content)
        data_tree = os.path.join(self.store.root, "data")
        copied = missing = 0
        for tname, tmeta in snap.get("tables", {}).items():
            src_t = os.path.join(src_tree, tname)
            # dictionaries: table-global and AUTHORITATIVE in the data tree
            # (flush_dicts always writes there, even while a mirror acts as
            # primary), so they flow ONE WAY data -> mirror; copying into
            # the data tree would clobber a fresher dictionary with a stale
            # mirror copy (r2 review finding)
            if os.path.normpath(dst_tree) != os.path.normpath(data_tree):
                dict_src = os.path.join(data_tree, tname)
                if os.path.isdir(dict_src):
                    for fn in os.listdir(dict_src):
                        if fn.startswith("dict_"):
                            dst_t = os.path.join(dst_tree, tname)
                            os.makedirs(dst_t, exist_ok=True)
                            copy_durable(os.path.join(dict_src, fn),
                                         os.path.join(dst_t, fn))
            for rel in tmeta.get("segfiles", {}).get(str(content), []):
                dst = os.path.join(dst_tree, tname, rel)
                if os.path.exists(dst):
                    continue
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                # fsync BEFORE _write_marker stamps the tree as synced: a
                # crash must not leave a synced marker over torn files
                # that FTS promotion and block-file repair would trust
                try:
                    copy_durable(os.path.join(src_t, rel), dst)
                except FileNotFoundError:
                    missing += 1
                    continue
                copied += 1
        return copied, missing

    def sync(self) -> dict[int, int]:
        """Bring every standby tree up to the current manifest version.
        Returns {content: replicated version}."""
        snap = self.store.manifest.snapshot()
        version = snap.get("version", 0)
        out: dict[int, int] = {}
        for content, standby in self._pairs():
            dst_tree = _tree_root(self.store.root, content, standby.preferred_role)
            if os.path.normpath(dst_tree) == os.path.normpath(
                    self.store.data_root(content)):
                continue   # standby tree IS the acting tree (misconfig guard)
            _copied, miss = self._copy_content(snap, content, dst_tree)
            if miss:
                # quarantined/lost acting files: the standby cannot reach
                # this version — leave its old marker, bar promotion past it
                standby.mode_synced = False
                continue
            _write_marker(dst_tree, content, version)
            out[content] = version
            standby.mode_synced = True
        return out

    def refresh_sync_state(self) -> None:
        """Recompute mode_synced from the durable standby-tree markers, so
        a stale standby is never promoted."""
        version = self.store.manifest.snapshot().get("version", 0)
        for content, standby in self._pairs():
            tree = _tree_root(self.store.root, content, standby.preferred_role)
            standby.mode_synced = tree_version(tree, content) == version

    def rebuild(self, content: int) -> int:
        """Full recovery (pg_basebackup-style): copy the acting primary's
        manifest-referenced files of ``content`` into the standby tree to
        completion and mark it synced. Returns files copied."""
        snap = self.store.manifest.snapshot()
        acting = self.config.acting_primary(content)
        if acting is None:
            raise RuntimeError(f"content {content} has no acting primary")
        standby_pref = (SegmentRole.PRIMARY
                        if acting.preferred_role is SegmentRole.MIRROR
                        else SegmentRole.MIRROR)
        dst_tree = _tree_root(self.store.root, content, standby_pref)
        copied, miss = self._copy_content(snap, content, dst_tree)
        if miss:
            # the acting tree itself is incomplete (quarantined files):
            # an honest rebuild is impossible — leave the standby unsynced
            # and the content's down markers in place for the operator
            return copied
        # dictionaries live authoritatively in the data tree and are not
        # deleted by a seg-file loss; nothing to rebuild for them
        _write_marker(dst_tree, content, snap.get("version", 0))
        try:
            self.config.entry(content, SegmentRole.MIRROR).mode_synced = True
        except KeyError:
            pass
        dead = [e for e in self.config.entries
                if e.content == content and e.status is SegmentStatus.DOWN]
        for e in dead:
            e.status = SegmentStatus.UP
        if dead:
            self.config.version += 1
        return copied
