from greengage_tpu.planner.logical import (  # noqa: F401
    Aggregate, Filter, Join, Limit, Motion, MotionKind, Plan, Project, Scan, Sort,
)
from greengage_tpu.planner.locus import Locus, LocusKind  # noqa: F401
from greengage_tpu.planner.planner import plan_query  # noqa: F401
