"""The parallelizer: locus propagation + Motion insertion.

Reference parity: cdbparallelize/apply_motion walking the plan and cutting
it at Motions (src/backend/cdb/cdbllize.c:132, cdbmutate.c:396), with the
join motion decision following cdbpath_motion_for_join
(src/backend/cdb/cdbpath.c:922): colocated -> no motion; one side already
hashed on its join keys -> redistribute the other; replicated side -> no
motion; otherwise min-cost of (redistribute both, broadcast one).

Aggregates follow the two/three-stage logic of cdbgroup.c:678: grouped by
the distribution key -> one phase; otherwise partial agg -> Redistribute by
group keys -> final merge; no group keys -> partial -> Gather -> final on
the coordinator (Entry locus).
"""

from __future__ import annotations

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.catalog import PolicyKind
from greengage_tpu.planner import cost as C
from greengage_tpu.planner import stats as S
from greengage_tpu.planner.locus import Locus, LocusKind
from greengage_tpu.planner.logical import (
    Aggregate, ColInfo, Filter, Join, Limit, Motion, MotionKind, Plan, Project,
    Scan, Sort, Union, Window,
)
from greengage_tpu.runtime.logger import counters


def _param_value(e) -> E.Expr | None:
    """A comparison operand whose VALUE is a hoisted parameter — a bare
    Param or the binder's numeric coercion Cast around one. The returned
    expression is stored in the pushed prune predicate and resolved to a
    concrete storage value at staging time (exec/executor._resolve_prune)."""
    if isinstance(e, E.Param):
        return e
    if isinstance(e, E.Cast) and isinstance(e.arg, E.Param):
        return e
    return None


def _year_days(y: int) -> int:
    """days-since-epoch of Jan 1 of ``y`` (host calendar math)."""
    return int((np.datetime64(f"{y:04d}-01-01") - np.datetime64("1970-01-01"))
               .astype(int))


def _year_prune(lhs, rhs, op, by_id) -> list[tuple] | None:
    """``extract_year(date_col) <op> int literal`` -> equivalent day-range
    prune predicates on the base column, or None. Exact because year is
    monotone non-decreasing in days-since-epoch."""
    if not (isinstance(lhs, E.Func) and lhs.name == "extract_year"
            and len(lhs.args) == 1 and isinstance(lhs.args[0], E.ColRef)
            and lhs.args[0].name in by_id
            and lhs.args[0].type.kind is T.Kind.DATE
            and isinstance(rhs, E.Literal) and rhs.value is not None
            and isinstance(rhs.value, (int, np.integer))
            and op in ("=", "<", "<=", ">", ">=")):
        return None
    y = int(rhs.value)
    if not 1 <= y < 9999:
        return None
    col = by_id[lhs.args[0].name]
    if op == "=":
        return [(col, ">=", _year_days(y)), (col, "<=", _year_days(y + 1) - 1)]
    if op == "<=":
        return [(col, "<=", _year_days(y + 1) - 1)]
    if op == "<":
        return [(col, "<=", _year_days(y) - 1)]
    if op == ">=":
        return [(col, ">=", _year_days(y))]
    return [(col, ">=", _year_days(y + 1))]      # op == ">"


class Planner:
    def __init__(self, catalog, store, numsegments: int,
                 force_multi_join: bool = False, feedback=None):
        self.catalog = catalog
        self.store = store
        self.nseg = numsegments
        self.force_multi_join = force_multi_join
        # feedback-driven row-scale corrections (planner/feedback.py):
        # None when cost_feedback is off or no store is wired in — the
        # session passes its FeedbackStore so observed actuals correct
        # est_rows per structural node digest
        self.feedback = feedback

    # ------------------------------------------------------------------
    def plan(self, node: Plan) -> Plan:
        # LIMIT directly under the top Gather is handled per-segment + host
        # re-limit; any deeper LIMIT needs single-segment execution (marked
        # here, enforced in _plan_limit)
        self._root_limits = set()
        top = node
        while isinstance(top, Limit):
            self._root_limits.add(id(top))
            top = top.child
        node = self._rec(node)
        # top: deliver to the coordinator
        if node.locus.kind is not LocusKind.ENTRY:
            node = self._gather(node)
        return node

    def _rec(self, node: Plan) -> Plan:
        m = getattr(self, "_plan_" + type(node).__name__.lower())
        out = m(node)
        if self.feedback is not None and isinstance(
                out, (Filter, Join, Aggregate)):
            # measured-traffic correction: scale the freshly computed
            # estimate by the digest's applied feedback scale BEFORE the
            # parent reads it, so motion choice, capacity sizing, and
            # admission all see corrected cardinalities. This is also
            # what supersedes a ParamRef.est_value seed: the populating
            # statement's literals seed the selectivity once, observed
            # actuals correct it forever after.
            out.est_rows = self.feedback.corrected_rows(out)
        return out

    # ------------------------------------------------------------------
    def _plan_scan(self, node: Scan) -> Plan:
        schema = self.catalog.get(node.table)
        pol = schema.policy
        nseg = pol.numsegments
        rows = sum(self.store.segment_rowcounts(node.table))
        node.est_rows = float(rows)
        if pol.kind is PolicyKind.HASH:
            by_name = {c.name: c.id for c in node.cols}
            try:
                ids = tuple(by_name[k] for k in pol.keys)
                node.locus = Locus.hashed(ids, nseg)
            except KeyError:
                # distribution key not scanned: still partitioned, key unknown
                node.locus = Locus.strewn(nseg)
        elif pol.kind is PolicyKind.REPLICATED:
            node.locus = Locus.segment_general(nseg)
        else:
            node.locus = Locus.strewn(nseg)
        return node

    def _plan_constrel(self, node) -> Plan:
        node.locus = Locus.strewn(self.nseg)
        node.est_rows = 1.0
        return node

    def _plan_filter(self, node: Filter) -> Plan:
        node.child = self._rec(node.child)
        node.locus = node.child.locus
        node.est_rows = node.child.est_rows * C.filter_selectivity(
            node.predicate, self._stats_lookup(node.child))
        self._maybe_direct_dispatch(node)
        return node

    def _maybe_direct_dispatch(self, node: Filter) -> None:
        """Scan-level predicate pushdown: (a) direct dispatch
        (cdbtargeteddispatch.c) when equality literals cover the full
        hash-distribution key; (b) zone-map prune predicates
        (PartitionSelector analog) for range/equality conjuncts over
        numeric/date columns — staging skips blocks they rule out."""
        child = node.child
        if not isinstance(child, Scan):
            return
        schema = self.catalog.get(child.table)
        by_id = {c.id: c.name for c in child.cols}
        found: dict[str, object] = {}
        prune: list[tuple] = []
        conjuncts = (list(node.predicate.args)
                     if isinstance(node.predicate, E.BoolOp)
                     and node.predicate.op == "and" else [node.predicate])
        flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
        for c in conjuncts:
            if not isinstance(c, E.Cmp):
                continue
            lhs, rhs, op = c.left, c.right, c.op
            if isinstance(rhs, (E.ColRef, E.Func)) \
                    and (isinstance(lhs, E.Literal) or _param_value(lhs)):
                lhs, rhs, op = rhs, lhs, flip.get(op, op)
            # extract_year(d) <op> literal (the TPC-DS date-filter shape):
            # year is monotone in days-since-epoch, so the conjunct
            # implies exact day bounds on the BASE date column — zone
            # maps / block indexes prune on those while the Func itself
            # stays fused in the device filter (ops/scalar.py)
            yp = _year_prune(lhs, rhs, op, by_id)
            if yp:
                prune.extend(yp)
                continue
            # hoisted literal (sql/paramize.py): the pushed predicate
            # carries the Param expression; the executor substitutes the
            # statement's current value at STAGING time, so zone-map /
            # block-index pruning stays value-exact while the compiled
            # program stays value-generic
            pp = _param_value(rhs)
            if pp is not None and isinstance(lhs, E.ColRef) \
                    and lhs.name in by_id \
                    and op in ("=", "<", "<=", ">", ">=") \
                    and lhs.type.kind in (T.Kind.INT32, T.Kind.INT64,
                                          T.Kind.DATE, T.Kind.DECIMAL,
                                          T.Kind.FLOAT64):
                prune.append((by_id[lhs.name], op, pp))
                continue
            if not (isinstance(lhs, E.ColRef) and isinstance(rhs, E.Literal)
                    and lhs.name in by_id):
                continue
            if op == "=":
                found[by_id[lhs.name]] = rhs.value
            if op in ("=", "<", "<=", ">", ">=") and rhs.value is not None \
                    and lhs.type.kind in (T.Kind.INT32, T.Kind.INT64,
                                          T.Kind.DATE, T.Kind.DECIMAL,
                                          T.Kind.FLOAT64):
                # keep ints EXACT (python int<->float comparisons are exact,
                # but float() conversion above 2^53 is not)
                v = rhs.value
                if isinstance(v, (bool, np.bool_)):
                    continue
                if isinstance(v, (int, np.integer)):
                    prune.append((by_id[lhs.name], op, int(v)))
                elif isinstance(v, (float, np.floating)):
                    prune.append((by_id[lhs.name], op, float(v)))
            elif op == "=" and lhs.type.kind is T.Kind.TEXT \
                    and rhs.value is not None \
                    and isinstance(rhs.value, (int, np.integer)):
                # dict-TEXT equality: the literal is already a storage
                # code. Codes are unordered, so ONLY equality is sound —
                # and it is, for both zone maps (code outside a block's
                # [min, max] cannot be present) and the block index
                prune.append((by_id[lhs.name], op, int(rhs.value)))
        if prune:
            child.prune_preds = tuple(prune)
            # access-path visibility: the staged read probes these
            # indexes' block sidecars (equality AND range ops)
            pruned_cols = {c for c, _, _ in prune}
            child.index_hits = tuple(sorted(
                name for name, d in getattr(schema, "indexes", {}).items()
                if d.get("column") in pruned_cols))
        if child.parts is not None and schema.is_partitioned:
            # static partition pruning from the same pushed conjuncts
            # (plan-time half of nodePartitionSelector.c); Param-valued
            # predicates have no value yet and cannot prune partitions
            # (paramize pins partition-key literals so this stays rare)
            child.parts_total = len(schema.partitions)
            keep = schema.prune_partitions(
                [(c, op, v) for c, op, v in prune
                 if not isinstance(v, E.Expr)])
            name_keep = {schema.partitions[i].storage_name(child.table)
                         for i in keep}
            child.parts = tuple(p for p in child.parts if p in name_keep)
        if schema.policy.kind is PolicyKind.HASH \
                and all(k in found for k in schema.policy.keys):
            child.direct_seg = self.store.segment_for_values(
                schema, {k: found[k] for k in schema.policy.keys})

    def _build_unique(self, plan: Plan, key_exprs) -> bool:
        """Join-key uniqueness for build-side selection: the structural
        dist-key heuristic, OR statistics — a key column whose NDV ≈ its
        table's row count is a key (covers REPLICATED dimensions like
        nation/region, which have no distribution key and were forced onto
        the duplicate-capable join path, compounding capacity estimates).
        A wrong stats guess is caught by the runtime dup flag and re-planned
        with force_multi_join."""
        if _keys_look_unique(plan, key_exprs):
            return True
        lookup = self._stats_lookup(plan)
        for e in key_exprs:
            if not isinstance(e, E.ColRef):
                continue
            org = _origin(plan, e.name)
            cs = lookup(e.name)
            if org is None or cs is None:
                continue
            try:
                ts = self.catalog.get(org[0]).stats
            except Exception:
                continue
            if ts is not None and ts.rows > 0 and cs.ndv >= 0.97 * ts.rows:
                return True
        return False

    # ---- statistics access (pg_statistic / ORCA stats-calculus analog) --
    def _stats_lookup(self, plan: Plan):
        """-> lookup(col_id) resolving a column through pass-through nodes
        to its base-table ColumnStats (None when unresolvable/unanalyzed)."""
        def lookup(col_id: str):
            org = _origin(plan, col_id)
            if org is None:
                return None
            try:
                schema = self.catalog.get(org[0])
            except Exception:
                return None
            ts = getattr(schema, "stats", None)
            if ts is None:
                return None
            return ts.columns.get(org[1])
        return lookup

    def _plan_project(self, node: Project) -> Plan:
        node.child = self._rec(node.child)
        child_locus = node.child.locus
        node.est_rows = node.child.est_rows
        if child_locus.kind is LocusKind.HASHED:
            # keep Hashed only if every distribution key passes through intact
            passthrough = {
                e.name for _, e in node.exprs if isinstance(e, E.ColRef)
            }
            if set(child_locus.keys) <= passthrough:
                # rename locus keys to projected ids
                rename = {
                    e.name: c.id for c, e in node.exprs if isinstance(e, E.ColRef)
                }
                node.locus = Locus.hashed(
                    tuple(rename[k] for k in child_locus.keys), child_locus.numsegments
                )
            else:
                node.locus = Locus.strewn(child_locus.numsegments)
        else:
            node.locus = child_locus
        return node

    # ------------------------------------------------------------------
    def _plan_join(self, node: Join) -> Plan:
        node.left = self._rec(node.left)
        node.right = self._rec(node.right)
        left, right = node.left, node.right
        nseg = self.nseg

        # Build side choice: the hash-join kernel requires unique build keys
        # (ops/join.py), so prefer the side whose join keys cover its scan's
        # distribution keys (PK-shaped: TPC-H dimension tables are
        # distributed by their primary key); among candidates pick the
        # smaller. Inner joins may swap freely (outputs are selected by id).
        if node.kind == "inner":
            lu = self._build_unique(left, node.left_keys)
            ru = self._build_unique(right, node.right_keys)
            swap = False
            if lu and not ru:
                swap = True
            elif lu == ru and left.est_rows < right.est_rows:
                swap = True
            if swap:
                node.left, node.right = right, left
                node.left_keys, node.right_keys = node.right_keys, node.left_keys
                left, right = node.left, node.right

        pairs = [
            (lk.name if isinstance(lk, E.ColRef) else None,
             rk.name if isinstance(rk, E.ColRef) else None)
            for lk, rk in zip(node.left_keys, node.right_keys)
        ]
        l2r = {l: r for l, r in pairs if l and r}
        r2l = {r: l for l, r in pairs if l and r}

        def colocated() -> bool:
            ll, rl = left.locus, right.locus
            if not (ll.kind is LocusKind.HASHED and rl.kind is LocusKind.HASHED):
                return False
            if ll.numsegments != rl.numsegments or len(ll.keys) != len(rl.keys):
                return False
            return all(l2r.get(a) == b for a, b in zip(ll.keys, rl.keys))

        def hashed_on_join_keys(locus: Locus, side_map: dict) -> bool:
            return (locus.kind is LocusKind.HASHED
                    and all(k in side_map for k in locus.keys))

        if node.kind == "cross":
            # broadcast the SMALLER side (cross-join outputs are selected
            # by id, so the sides may swap freely); without the swap a
            # 1-row constant relation on the left would broadcast the
            # whole table on the right
            if right.locus.is_partitioned and (
                    left.est_rows < right.est_rows
                    or left.locus.kind is LocusKind.SEGMENT_GENERAL):
                # also swap a REPLICATED left: as the build side it needs
                # no motion at all, where keeping it on the left forces a
                # broadcast of the partitioned right
                node.left, node.right = node.right, node.left
                left, right = right, left
            if right.locus.kind is not LocusKind.SEGMENT_GENERAL:
                node.right = self._broadcast(right)
            node.locus = left.locus
        elif right.locus.kind in (LocusKind.SEGMENT_GENERAL, LocusKind.GENERAL):
            node.locus = left.locus
        elif left.locus.kind in (LocusKind.SEGMENT_GENERAL, LocusKind.GENERAL):
            node.locus = right.locus if node.kind == "inner" else left.locus
            if node.kind != "inner":
                # outer/semi probe side replicated: broadcast build instead
                node.right = self._broadcast(right)
                node.locus = left.locus
        elif colocated():
            node.locus = left.locus
        elif hashed_on_join_keys(left.locus, l2r):
            # move build side to match probe's existing distribution
            exprs = [node.right_keys[[l for l, _ in pairs].index(k)]
                     for k in left.locus.keys]
            node.right = self._redistribute(right, exprs,
                                            tuple(l2r[k] for k in left.locus.keys))
            node.locus = left.locus
        elif hashed_on_join_keys(right.locus, r2l) and node.kind == "inner":
            exprs = [node.left_keys[[r for _, r in pairs].index(k)]
                     for k in right.locus.keys]
            node.left = self._redistribute(left, exprs,
                                           tuple(r2l[k] for k in right.locus.keys))
            node.locus = right.locus
        else:
            # neither side usable: redistribute both vs broadcast build side.
            # Calibrated comparison (cost.py): a broadcast build is sorted
            # FULL-SIZE on every chip (~40 ns/row/operand), so the ICI bytes
            # it saves must beat that extra build work — a bytes-only model
            # systematically over-broadcasts mid-size relations.
            lw = C.row_width(left.out_cols())
            rw = C.row_width(right.out_cols())
            nk = max(len(pairs), 1)
            redist = (C.motion_cost("redistribute", left.est_rows, lw, nseg)
                      + C.motion_cost("redistribute", right.est_rows, rw, nseg)
                      + C.join_build_cost(right.est_rows, nk, nseg))
            bcast = (C.motion_cost("broadcast", right.est_rows, rw, nseg)
                     + C.join_build_cost(right.est_rows, nk, nseg,
                                         replicated=True))
            if bcast < redist:
                node.right = self._broadcast(right)
                node.locus = left.locus
            else:
                lids = tuple(l for l, _ in pairs)
                rids = tuple(r for _, r in pairs)
                node.left = self._redistribute(left, list(node.left_keys), lids)
                node.right = self._redistribute(right, list(node.right_keys), rids)
                node.locus = node.left.locus
        # output cardinality: with ANALYZE stats, |L||R|/max(key NDVs);
        # fallback to the round-1 max() guess
        llook = self._stats_lookup(left)
        rlook = self._stats_lookup(right)
        est = None
        sel = 1.0
        for lk, rk in zip(node.left_keys, node.right_keys):
            ls = llook(lk.name) if isinstance(lk, E.ColRef) else None
            rs = rlook(rk.name) if isinstance(rk, E.ColRef) else None
            if ls is None or rs is None or ls.ndv <= 0 or rs.ndv <= 0:
                sel = None
                break
            # histogram join calculus (MCV x MCV + aligned-histogram
            # remainder, stats.join_selectivity); NDV division fallback
            ksel = S.join_selectivity(ls, rs,
                                      (lk.type.kind, rk.type.kind))
            if ksel is None:
                ksel = 1.0 / max(ls.ndv, rs.ndv)
            sel *= ksel * (1.0 - ls.null_frac) * (1.0 - rs.null_frac)
        if sel is not None:
            est = max(left.est_rows * right.est_rows * sel, 1.0)
        node.est_rows = est if est is not None else max(left.est_rows, right.est_rows)
        if node.kind in ("semi", "anti"):
            node.est_rows = left.est_rows * 0.5
        # build-side duplicate keys force the CSR multi-match kernel for
        # inner/left (semi/anti only need existence, the plain table is
        # fine); the multi kernel handles per-match residual
        # disqualification with one-null-row-per-probe collapse.
        if node.kind in ("inner", "left"):
            if self.force_multi_join or not self._build_unique(
                    node.right, node.right_keys):
                node.multi = True
                # duplicate fanout multiplies output rows; nudge the
                # estimate so operators above size their tables for it
                node.est_rows = max(node.est_rows, left.est_rows * 2.0)
        elif node.kind in ("semi", "anti") and node.residual is not None:
            # residual EXISTS correlation must test EVERY duplicate build
            # row (any-match): route through the CSR expansion. Stash the
            # PAIR estimate (|L||R|/max key NDV) so the compiler sizes the
            # expansion from stats instead of overflowing the first run
            node.multi = True
            if sel is not None:
                node.expand_est = max(
                    left.est_rows * right.est_rows * sel, 1.0)
        # build-side key bounds for the packed/narrowed hash table
        # (ops/join.py pack_join_keys): probe values outside the build's
        # bounds simply never match, so only the BUILD side's stats matter
        node.key_bounds = self._key_bounds(node.right, node.right_keys)
        self._maybe_direct_join(node)
        self._maybe_dynamic_partition_prune(node)
        return node

    def _maybe_dynamic_partition_prune(self, node: Join) -> None:
        """Join-driven runtime partition elimination (the
        PartitionSelector role, src/backend/executor/
        nodePartitionSelector.c:1): when a partitioned probe joins a
        small build table ON ITS PARTITION KEY, annotate the probe scan
        so STAGING first evaluates the build's (pushable) filter on the
        host, collects the surviving key values, and skips whole child
        partitions no value can land in — partitions the static pruner
        could never eliminate because the selecting predicate lives on
        the other table. Inner/semi only: a left join keeps unmatched
        probe rows, which pruned partitions would drop."""
        if node.kind not in ("inner", "semi") or getattr(node, "null_aware",
                                                         False):
            return
        for lk, rk in zip(node.left_keys, node.right_keys):
            if not (isinstance(lk, E.ColRef) and isinstance(rk, E.ColRef)):
                continue
            lorg = _origin(node.left, lk.name)
            rorg = _origin(node.right, rk.name)
            if lorg is None or rorg is None or lorg[0] == rorg[0]:
                continue
            try:
                schema = self.catalog.get(lorg[0])
            except Exception:
                continue
            if not schema.is_partitioned or schema.partition_by[1] != lorg[1]:
                continue
            scan = _find_single_scan(node.left, lorg[0])
            dim_scan = _find_single_scan(node.right, rorg[0])
            if scan is None or dim_scan is None or scan.parts is None \
                    or dim_scan.parts is not None:
                continue
            if getattr(scan, "dyn_prune", None) is not None:
                continue
            try:
                dim_rows = sum(self.store.segment_rowcounts(rorg[0]))
            except Exception:
                continue
            if dim_rows > 200_000:   # host pre-pass must stay cheap
                continue
            scan.dyn_prune = (rorg[0], tuple(dim_scan.prune_preds or ()),
                              rorg[1])
            return

    def _maybe_direct_join(self, node: Join) -> None:
        """Dense integer build keys (sequence/surrogate PKs): address the
        build table directly by (key - min) — one scatter to build, one
        gather to probe (ops/join.py build_direct). Decided from ANALYZE
        min/max; stale stats surface as a build overflow and the retry
        tier falls back to the hash table."""
        if node.multi or node.kind == "cross" or len(node.right_keys) != 1:
            return
        rk = node.right_keys[0]
        if not isinstance(rk, E.ColRef) or rk.type.kind not in (
                T.Kind.INT32, T.Kind.INT64, T.Kind.DATE):
            return
        org = _origin(node.right, rk.name)
        cs = self._stats_lookup(node.right)(rk.name)
        if org is None or cs is None or cs.min is None or cs.max is None:
            return
        try:
            ts = self.catalog.get(org[0]).stats
        except Exception:
            return
        rows = ts.rows if ts is not None else 0
        domain = int(cs.max) - int(cs.min) + 1
        # bound by the base table's density (sequence-like keys) and by a
        # hard table-memory cap. A filtered build over a big domain still
        # wins — table init is one bandwidth pass and the scatter costs
        # only the build rows, vs the iterative hash build's many rounds —
        # and the domain memory is charged to the vmem admission estimate.
        if domain <= 0 or domain > max(4 * max(rows, 1), 1 << 21) \
                or domain > (1 << 27):
            return
        node.direct_lo = int(cs.min)
        node.direct_domain = domain

    # ------------------------------------------------------------------
    def _plan_aggregate(self, node: Aggregate) -> Plan:
        node.child = self._rec(node.child)
        child = node.child
        key_ids = tuple(
            e.name for _, e in node.group_keys if isinstance(e, E.ColRef)
        )
        groups = min(self._est_groups(node, child),
                     self._group_domain_bound(node.group_keys))

        if not node.group_keys:
            # scalar aggregate: partial everywhere -> broadcast the (tiny)
            # partial states -> identical final merge on every segment
            # (SEGMENT_GENERAL result; Gather later reads one segment).
            # Keeps HAVING/projections above it on-device with no host path.
            # SINGLE_QE children go through the partial path too: a
            # single-phase scalar agg marks its output row used on EVERY
            # segment while the data lives on one, so the gather would
            # return one row per segment (advisor finding r1).
            if child.locus.kind in (LocusKind.ENTRY,
                                    LocusKind.SEGMENT_GENERAL):
                node.phase = "single"
                node.locus = child.locus
                node.est_rows = 1
                return node
            partial = self._make_partial(node)
            moved = self._broadcast(partial)
            final = self._make_final(node, partial, moved)
            final.est_rows = 1
            final.locus = Locus.segment_general(self.nseg)
            return final

        if (child.locus.kind is LocusKind.HASHED and child.locus.hashed_on(key_ids)) \
                or child.locus.kind in (LocusKind.ENTRY, LocusKind.SINGLE_QE,
                                        LocusKind.SEGMENT_GENERAL):
            node.phase = "single"
            node.locus = child.locus
            node.est_rows = groups
            node.key_bounds = self._key_bounds(child, [e for _, e in node.group_keys])
            return node

        # Agg placement is a COSTED alternative (the cdbgroup.c one-stage vs
        # two-stage choice ORCA explores as memo alternatives):
        #   two-phase: partial local -> redistribute states -> final merge
        #   one-phase: redistribute raw rows by group keys -> single agg
        # When groups ~ rows (high-NDV keys like Q3's l_orderkey), the
        # partial pass reduces nothing — it pays a full sort-agg AND moves
        # nearly the same bytes, so shipping raw rows wins.
        nk = len(node.group_keys)
        na = max(len(node.aggs), 1)
        child_w = C.row_width(child.out_cols())
        state_w = 8.0 * (nk + 2 * na)    # @s/@c/@m partial state columns
        partial_rows = min(child.est_rows, groups * max(self.nseg, 1))
        two_cost = (C.agg_cost(child.est_rows, groups, nk, na, child_w, self.nseg)
                    + C.motion_cost("redistribute", partial_rows, state_w, self.nseg)
                    + C.agg_cost(partial_rows, groups, nk, na, state_w, self.nseg))
        one_cost = (C.motion_cost("redistribute", child.est_rows, child_w, self.nseg)
                    + C.agg_cost(child.est_rows, groups, nk, na, child_w, self.nseg))
        all_colrefs = all(isinstance(e, E.ColRef) for _, e in node.group_keys)
        if all_colrefs and child.locus.is_partitioned and one_cost < two_cost:
            moved = self._redistribute(
                node.child, [e for _, e in node.group_keys], key_ids)
            node.child = moved
            node.phase = "single"
            node.locus = moved.locus
            node.est_rows = groups
            node.key_bounds = self._key_bounds(moved, [e for _, e in node.group_keys])
            return node

        # two-phase: partial local -> redistribute by group keys -> final
        partial = self._make_partial(node)
        partial.key_bounds = self._key_bounds(node.child, [e for _, e in partial.group_keys])
        key_exprs = [E.ColRef(c.id, c.type) for c, _ in partial.group_keys]
        moved = self._redistribute(
            partial, key_exprs, tuple(c.id for c, _ in partial.group_keys))
        final = self._make_final(node, partial, moved)
        final.locus = moved.locus
        final.est_rows = groups
        final.key_bounds = self._key_bounds(moved, [e for _, e in node.group_keys])
        return final

    def _key_bounds(self, child: Plan, key_exprs) -> list:
        """Per-key (lo, hi) integer bounds from ANALYZE stats — feeds the
        packed single-operand group/order sorts and narrowed join tables
        (ops/agg.py pack_keys, ops/sort.py pack_order_keys,
        ops/join.py pack_join_keys). None for unanalyzed/computed/
        non-integer keys; a stale bound is caught at runtime by the
        pack-violation flag and re-run unpacked."""
        lookup = self._stats_lookup(child)
        out = []
        for e in key_exprs:
            b = None
            if isinstance(e, E.ColRef) and e.type.kind in (
                    T.Kind.INT32, T.Kind.INT64, T.Kind.DATE):
                cs = lookup(e.name)
                if cs is not None and cs.min is not None and cs.max is not None:
                    try:
                        b = (int(cs.min), int(cs.max))
                    except (TypeError, ValueError, OverflowError):
                        b = None
            out.append(b)
        return out

    def _est_groups(self, node: Aggregate, child: Plan) -> float:
        """NDV-product estimate when every group key resolves to analyzed
        base columns; sqrt heuristic otherwise."""
        lookup = self._stats_lookup(child)
        ndvs = []
        for _, e in node.group_keys:
            cs = lookup(e.name) if isinstance(e, E.ColRef) else None
            if cs is None or cs.ndv <= 0:
                return C.est_groups(child.est_rows)
            ndvs.append(cs.ndv)
        return C.est_groups(child.est_rows, ndvs)

    def _group_domain_bound(self, group_keys) -> float:
        """Hard upper bound on distinct groups when every key has a known
        finite domain: TEXT keys can't exceed their dictionary size, BOOL
        keys can't exceed 2 (+NULL). Exact for TPC-H flag/status columns —
        keeps slot tables and result transfers at true size."""
        from greengage_tpu import types as T

        prod = 1.0
        for ci, e in group_keys:
            if ci.type.kind is T.Kind.TEXT and ci.dict_ref is not None:
                prod *= max(len(self.store.dictionary(*ci.dict_ref)), 1) + 1
            elif ci.type.kind is T.Kind.BOOL:
                prod *= 3
            else:
                return float("inf")
            if prod > 1e12:
                return float("inf")
        return prod

    def _make_partial(self, node: Aggregate) -> Aggregate:
        partial = Aggregate(
            child=node.child, group_keys=node.group_keys, aggs=node.aggs,
            phase="partial")
        partial.locus = node.child.locus
        groups = min(self._est_groups(node, node.child),
                     self._group_domain_bound(node.group_keys))
        partial.est_rows = min(node.child.est_rows, groups * max(self.nseg, 1))
        return partial

    def _make_final(self, node: Aggregate, partial: Aggregate, moved: Plan) -> Aggregate:
        final = Aggregate(
            child=moved, group_keys=node.group_keys, aggs=node.aggs, phase="final")
        return final

    # ------------------------------------------------------------------
    def _plan_union(self, node: Union) -> Plan:
        node.inputs = [self._rec(c) for c in node.inputs]
        # branches concatenate per segment (replicated branches are masked
        # to one segment by the compiler to avoid row duplication)
        node.locus = Locus.strewn(self.nseg)
        node.est_rows = sum(c.est_rows for c in node.inputs)
        return node

    # unordered global windows: every function is a whole-mesh collective
    GLOBAL_DIST = {"row_number", "count", "sum", "avg", "min", "max",
                   "first_value", "last_value"}
    # ordered global windows computable IN PLACE from all-gathered sorted
    # key runs: ranks are counted positions, ntile is arithmetic on
    # (rank, count), lag/lead/first/last resolve rank±offset via a lookup
    # into the gathered runs — rows never move
    ORDERED_GLOBAL = {"row_number", "rank", "dense_rank", "ntile",
                      "lag", "lead", "first_value", "last_value"}
    # range-repartitioned global windows (one balanced Redistribute by
    # sampled splitters of the leading key; segments own contiguous key
    # ranges, so peer groups are whole per segment and running aggregates
    # stitch with per-segment prefix totals)
    RANGE_GLOBAL = ORDERED_GLOBAL | {"sum", "count", "avg", "min", "max"}

    def _plan_window(self, node: Window) -> Plan:
        node.child = self._rec(node.child)
        child = node.child
        key_ids = tuple(e.name for e in node.partition_keys
                        if isinstance(e, E.ColRef))
        if not node.partition_keys:
            if (not node.order_keys and node.frame is None
                    and child.locus.is_partitioned
                    and all(f[1] in self.GLOBAL_DIST for f in node.wfuncs)):
                # unordered global window: the whole table is one
                # partition, so every function is a mesh collective —
                # rows stay in place instead of funneling to one chip
                # (VERDICT r3 weak #9)
                node.global_mode = True
                node.locus = child.locus
                node.est_rows = child.est_rows
                counters.inc("window_gather_free_total")
                return node
            if (node.order_keys and node.frame is None
                    and child.locus.is_partitioned):
                if all(f[1] in self.ORDERED_GLOBAL for f in node.wfuncs):
                    # ordered global ranking family over integer/date/
                    # decimal keys: each row's global rank AND the global
                    # row count are computable IN PLACE from all-gathered
                    # per-segment sorted key runs — no funnel, no row
                    # motion. Multi-key and nullable shapes pack keys into
                    # one uint64 using EXACT storage bounds from block
                    # zone maps (+1 null bit per key); a single key
                    # without usable bounds falls back to the full-64-bit
                    # encoding with runtime NULL classes (see compile)
                    spec = self._ordered_global_spec(child, node.order_keys)
                    if spec is not None:
                        node.global_mode = "ordered"
                        node.gkey_spec = spec
                        node.locus = child.locus
                        node.est_rows = child.est_rows
                        counters.inc("window_gather_free_total")
                        return node
                if all(f[1] in self.RANGE_GLOBAL for f in node.wfuncs):
                    # keys that cannot pack into the uint64 rank space
                    # (multi-key over wide domains, float keys, running
                    # aggregates): range-repartition by sampled splitters
                    # of the LEADING key — one balanced Redistribute
                    # instead of the one-chip funnel. Equal leading keys
                    # co-locate, so peer groups stay whole per segment and
                    # the segment-local kernels stitch with per-segment
                    # offsets (exec/compile.py _c_window_global_range)
                    rspec = self._range_window_spec(node.order_keys)
                    if rspec is not None:
                        m = Motion(MotionKind.REDISTRIBUTE, child,
                                   hash_exprs=[rspec["expr"]])
                        m.range_spec = rspec
                        m.locus = Locus.strewn(self.nseg)
                        m.est_rows = child.est_rows
                        node.child = m
                        node.global_mode = "range"
                        node.gkey_spec = {"mode": "range", **rspec}
                        node.locus = m.locus
                        node.est_rows = child.est_rows
                        counters.inc("window_gather_free_total")
                        return node
            # exotic global window (explicit frames, unsupported key or
            # function shapes): all rows to a single segment
            if child.locus.is_partitioned:
                counters.inc("window_funnel_total")
                const = E.Literal(0, T.INT64)
                m = Motion(MotionKind.REDISTRIBUTE, child, hash_exprs=[const])
                m.locus = Locus(LocusKind.SINGLE_QE, (), self.nseg)
                m.est_rows = child.est_rows
                node.child = m
        elif child.locus.kind is LocusKind.HASHED and child.locus.hashed_on(key_ids):
            pass   # partitions already whole per segment
        elif child.locus.is_partitioned:
            m = self._redistribute(child, list(node.partition_keys), key_ids)
            node.child = m
        node.locus = node.child.locus
        node.est_rows = child.est_rows
        return node

    _RANGE_KINDS = (T.Kind.INT32, T.Kind.INT64, T.Kind.DATE, T.Kind.DECIMAL,
                    T.Kind.FLOAT64)

    def _range_window_spec(self, order_keys):
        """Sampled-splitter range-repartition spec from the LEADING order
        key, or None. The key only needs an order-preserving uint64
        encoding (sign-flip ints / IEEE floats) — no bounds, no packing:
        routing by range just needs comparisons, and the local sort above
        handles the full key list with the general multi-operand path."""
        e, desc, nf = order_keys[0]
        if e.type.kind not in self._RANGE_KINDS \
                and not getattr(e, "_rank_space", False):
            return None
        if nf is None:
            nf = bool(desc)
        kind = "float" if e.type.kind is T.Kind.FLOAT64 else "int"
        return {"expr": e, "desc": bool(desc), "nulls_first": bool(nf),
                "kind": kind}

    def _ordered_global_spec(self, child: Plan, order_keys):
        """Distribution spec for in-place global ranking, or None (-> the
        one-chip funnel). Reference never funnels — it sorts distributed
        (nodeWindowAgg.c + tuplesort); this is the TPU-first equivalent:
        pack the ORDER BY keys order-preservingly into one uint64 so rank
        = a counted position over all-gathered sorted key runs.

        PG null placement applies: NULLS LAST asc / FIRST desc unless
        explicit. `packed` needs every key to be an INT32/INT64/DATE/
        DECIMAL ColRef with exact zone-map bounds and total width <= 64
        bits; `full64` handles ONE key of any such expression — or a
        FLOAT64 one (IEEE monotone encoding) — with no bounds at all
        (runtime NULL classes)."""
        INTISH = (T.Kind.INT32, T.Kind.INT64, T.Kind.DATE, T.Kind.DECIMAL)
        resolved = []
        for e, desc, nf in order_keys:
            if e.type.kind not in INTISH + (T.Kind.FLOAT64,) \
                    and not getattr(e, "_rank_space", False):
                return None   # rank-space TEXT keys are bounded ints
            if nf is None:
                nf = bool(desc)
            resolved.append((e, bool(desc), bool(nf)))
        fields: list | None = []
        total = 0
        for e, desc, nf in resolved:
            if getattr(e, "_rank_space", False):
                bounds = (0, (1 << e._rank_bits) - 1)
            elif e.type.kind is T.Kind.FLOAT64:
                bounds = None   # floats never pack; full64 handles one
            else:
                org = _origin(child, e.name) if isinstance(e, E.ColRef) \
                    else None
                bounds = self.store.column_bounds(*org) if org else None
            if bounds is None:
                fields = None
                break
            lo, hi = int(bounds[0]), int(bounds[1])
            bits = max((hi - lo).bit_length(), 1)
            total += bits + 1       # +1 null flag per field
            fields.append({"expr": e, "desc": desc, "nulls_first": nf,
                           "lo": lo, "hi": hi, "bits": bits})
        if fields is not None and total <= 64:
            return {"mode": "packed", "fields": fields}
        if len(resolved) == 1:
            e, desc, nf = resolved[0]
            return {"mode": "full64", "expr": e, "desc": desc,
                    "nulls_first": nf,
                    "kind": ("float" if e.type.kind is T.Kind.FLOAT64
                             else "int")}
        return None

    def _plan_sort(self, node: Sort) -> Plan:
        node.child = self._rec(node.child)
        node.locus = node.child.locus
        node.est_rows = node.child.est_rows
        node.key_bounds = self._key_bounds(
            node.child, [e for e, _, _ in node.keys])
        return node

    def _plan_limit(self, node: Limit) -> Plan:
        node.child = self._rec(node.child)
        child = node.child
        # a LIMIT buried inside the plan (subquery) must be GLOBAL: move all
        # rows to one segment first (SingleQE locus via constant-key
        # redistribute). The top-of-plan LIMIT keeps the cheaper per-segment
        # truncation + host re-limit. SEGMENT_GENERAL children are already
        # identical everywhere, so per-segment truncation is globally right.
        if id(node) not in self._root_limits and child.locus.is_partitioned:
            const = E.Literal(0, T.INT64)
            if isinstance(child, Sort):
                m = Motion(MotionKind.REDISTRIBUTE, child.child, hash_exprs=[const])
                m.locus = Locus(LocusKind.SINGLE_QE, (), self.nseg)
                m.est_rows = child.child.est_rows
                child.child = m
                child.locus = m.locus
            else:
                m = Motion(MotionKind.REDISTRIBUTE, child, hash_exprs=[const])
                m.locus = Locus(LocusKind.SINGLE_QE, (), self.nseg)
                m.est_rows = child.est_rows
                node.child = m
                child = m
        node.locus = child.locus
        if node.limit is not None:
            node.est_rows = min(child.est_rows, node.limit + node.offset)
        else:
            node.est_rows = child.est_rows
        return node

    # ------------------------------------------------------------------
    def _redistribute(self, child: Plan, exprs: list, key_ids: tuple) -> Motion:
        m = Motion(MotionKind.REDISTRIBUTE, child, hash_exprs=list(exprs))
        m.locus = Locus.hashed(key_ids, self.nseg) if all(key_ids) else Locus.strewn(self.nseg)
        m.est_rows = child.est_rows
        return m

    def _broadcast(self, child: Plan) -> Motion:
        m = Motion(MotionKind.BROADCAST, child)
        m.locus = Locus.segment_general(self.nseg)
        m.est_rows = child.est_rows * self.nseg
        return m

    def _gather(self, child: Plan) -> Motion:
        merge_keys = None
        if isinstance(child, Sort):
            merge_keys = child.keys
        elif isinstance(child, Limit) and isinstance(child.child, Sort):
            merge_keys = child.child.keys
        m = Motion(MotionKind.GATHER, child, merge_keys=merge_keys)
        m.locus = Locus.entry()
        m.est_rows = child.est_rows
        return m


def _find_single_scan(plan: Plan, table: str):
    """The unique Scan of ``table`` in the subtree, or None if absent or
    scanned more than once (two scans must not share one prune)."""
    found = None
    stack = [plan]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan) and p.table == table:
            if found is not None:
                return None
            found = p
        stack.extend(p.children)
    return found


def _origin(plan: Plan, col_id: str):
    """Resolve a column id through pass-through nodes to its base-table
    (table, column) origin — None for computed/derived columns. The stats
    machinery uses this instead of threading provenance through every
    binder expression."""
    if isinstance(plan, Scan):
        for c in plan.cols:
            if c.id == col_id:
                return (plan.table, c.name)
        return None
    if isinstance(plan, (Filter, Motion, Limit, Sort, Window)):
        return _origin(plan.children[0], col_id)
    if isinstance(plan, Project):
        for c, e in plan.exprs:
            if c.id == col_id:
                return _origin(plan.child, e.name) if isinstance(e, E.ColRef) else None
        return None
    if isinstance(plan, Join):
        return _origin(plan.left, col_id) or _origin(plan.right, col_id)
    if isinstance(plan, Aggregate):
        for c, e in plan.group_keys:
            if c.id == col_id:
                return _origin(plan.child, e.name) if isinstance(e, E.ColRef) else None
        return None
    return None


def _keys_look_unique(plan: Plan, key_exprs) -> bool:
    """Heuristic uniqueness: the join keys include a column set that is some
    underlying Scan's full hash-distribution key (tables are conventionally
    distributed by primary key). Pass-through nodes are traversed; joins
    against a unique side preserve the probe side's keys."""
    ids = {e.name for e in key_exprs if isinstance(e, E.ColRef)}
    if not ids:
        return False
    return _scan_covers(plan, ids)


def _scan_covers(plan: Plan, ids: set) -> bool:
    if isinstance(plan, Scan):
        by_id = {c.id: c.name for c in plan.cols}
        names = {by_id[i] for i in ids if i in by_id}
        pol = plan.locus
        from greengage_tpu.planner.locus import LocusKind as LK

        if pol is not None and pol.kind is LK.HASHED:
            key_names = set()
            for c in plan.cols:
                if c.id in pol.keys:
                    key_names.add(c.name)
            return bool(key_names) and key_names <= names
        return False
    if isinstance(plan, (Filter, Motion, Limit, Sort)):
        return _scan_covers(plan.children[0], ids)
    if isinstance(plan, Project):
        # translate projected ids back to child ids for pass-through refs
        back = {c.id: e.name for c, e in plan.exprs if isinstance(e, E.ColRef)}
        child_ids = {back.get(i) for i in ids}
        if None in child_ids:
            return False
        return _scan_covers(plan.child, child_ids)
    if isinstance(plan, Aggregate):
        # grouped output is unique on its full group key set
        key_ids = {c.id for c, _ in plan.group_keys}
        return bool(key_ids) and key_ids <= ids
    if isinstance(plan, Join):
        # unique(left) x unique-matched build keeps left keys unique
        return _scan_covers(plan.left, ids)
    return False


def plan_query(root: Plan, catalog, store, numsegments: int,
               force_multi_join: bool = False, feedback=None) -> Plan:
    return Planner(catalog, store, numsegments, force_multi_join,
                   feedback=feedback).plan(root)
