"""Locus algebra — the distribution type system of plans.

Reference parity: CdbPathLocus (src/backend/cdb/cdbpathlocus.h:29-49, .c) —
every plan node carries *where its rows live*:

  ENTRY           on the coordinator (QD) only
  SINGLE_QE       on exactly one segment
  GENERAL         logically everywhere (constants); safe to join anywhere
  SEGMENT_GENERAL replicated tables: full copy on every segment
  HASHED          partitioned by hash of key columns over numsegments
  STREWN          partitioned with no known key (DISTRIBUTED RANDOMLY,
                  or a projection that dropped its hash keys)

``numsegments`` travels with the locus (gp_policy.h:35) so plans remain
correct across mixed-width tables during expansion.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class LocusKind(enum.Enum):
    ENTRY = "Entry"
    SINGLE_QE = "SingleQE"
    GENERAL = "General"
    SEGMENT_GENERAL = "SegmentGeneral"
    HASHED = "Hashed"
    STREWN = "Strewn"


@dataclass(frozen=True)
class Locus:
    kind: LocusKind
    keys: tuple[str, ...] = ()   # hash key column ids (HASHED only)
    numsegments: int = 0

    @staticmethod
    def entry() -> "Locus":
        return Locus(LocusKind.ENTRY)

    @staticmethod
    def hashed(keys, nseg: int) -> "Locus":
        return Locus(LocusKind.HASHED, tuple(keys), nseg)

    @staticmethod
    def strewn(nseg: int) -> "Locus":
        return Locus(LocusKind.STREWN, (), nseg)

    @staticmethod
    def segment_general(nseg: int) -> "Locus":
        return Locus(LocusKind.SEGMENT_GENERAL, (), nseg)

    @property
    def is_partitioned(self) -> bool:
        return self.kind in (LocusKind.HASHED, LocusKind.STREWN)

    def hashed_on(self, cols: tuple[str, ...]) -> bool:
        """True if rows are partitioned by exactly these columns (order-
        insensitive subset rule: distribution keys ⊆ cols means co-location
        for grouping; joins need the full equality-key correspondence)."""
        return self.kind is LocusKind.HASHED and set(self.keys) <= set(cols) and bool(self.keys)

    def describe(self) -> str:
        if self.kind is LocusKind.HASHED:
            return f"Hashed({', '.join(self.keys)}) x{self.numsegments}"
        if self.is_partitioned or self.kind is LocusKind.SEGMENT_GENERAL:
            return f"{self.kind.value} x{self.numsegments}"
        return self.kind.value
