"""Table statistics: ANALYZE + estimators — the pg_statistic / ORCA
statistics calculus analog.

Reference parity: ANALYZE's sample-based collection (the reference gathers
NDV/MCV/histograms into pg_statistic; ORCA consumes them through
libnaucrates' statistics objects, src/backend/gporca/libnaucrates/src/
statistics/). We collect, per column: exact min/max/null fraction (one
vectorized pass) and sample-based NDV using the Haas-Stokes (Duj1)
estimator PostgreSQL uses in analyze.c. MCVs are kept for low-cardinality
columns so equality selectivity on skewed columns is grounded.

Stats feed: filter selectivities, GROUP BY cardinality (est_groups),
join output cardinality, and motion/agg capacity sizing — where round 1
used constants (planner/cost.py), which cost a full XLA recompile per
mis-estimate via the overflow-tier retry.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from greengage_tpu import types as T

SAMPLE_ROWS = 240_000   # ~ the reference's default_statistics_target regime


HIST_BUCKETS = 32   # equi-depth buckets per numeric/date column


@dataclass
class ColumnStats:
    ndv: float = 0.0            # estimated distinct values (excl. NULL)
    null_frac: float = 0.0
    min: float | None = None    # storage-encoded (dates=days, decimals=scaled)
    max: float | None = None
    mcv: list = field(default_factory=list)     # [(encoded value, fraction)]
    # equi-depth histogram: HIST_BUCKETS+1 boundary values (sample
    # quantiles), each bucket holding ~1/HIST_BUCKETS of the non-null
    # mass — the pg_statistic histogram_bounds / CHistogram bucket
    # calculus analog. Range selectivity reads bucket positions instead of
    # linearly interpolating [min, max], which is wrong on any skewed
    # distribution (and every mis-estimate here costs an XLA recompile
    # tier).
    hist: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"ndv": self.ndv, "null_frac": self.null_frac,
                "min": self.min, "max": self.max, "mcv": self.mcv,
                "hist": self.hist}

    @staticmethod
    def from_dict(d: dict) -> "ColumnStats":
        return ColumnStats(d.get("ndv", 0.0), d.get("null_frac", 0.0),
                           d.get("min"), d.get("max"),
                           [tuple(x) for x in d.get("mcv", [])],
                           list(d.get("hist", [])))


@dataclass
class TableStats:
    rows: int = 0
    version: int = -1           # manifest version when analyzed
    columns: dict = field(default_factory=dict)   # name -> ColumnStats
    # content hash of the table's manifest entry at analyze time: the
    # `gg analyzedb` incremental gate (analyzedb's mtime+state tracking
    # analog) — unchanged hash = stats still describe the data
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {"rows": self.rows, "version": self.version,
                "fingerprint": self.fingerprint,
                "columns": {n: c.to_dict() for n, c in self.columns.items()}}

    @staticmethod
    def from_dict(d: dict) -> "TableStats":
        return TableStats(d.get("rows", 0), d.get("version", -1),
                          {n: ColumnStats.from_dict(c)
                           for n, c in d.get("columns", {}).items()},
                          d.get("fingerprint", ""))


def _haas_stokes(n_sample: int, d_sample: int, f1: int, total_rows: int) -> float:
    """Duj1 NDV estimator (what analyze.c uses): scale the sample's distinct
    count by how many singletons it saw. All-distinct samples extrapolate to
    the full table; no-singleton samples are near-complete domains."""
    if n_sample == 0:
        return 0.0
    if d_sample >= n_sample:
        return float(total_rows)
    if f1 == 0:
        return float(d_sample)
    n, d = float(n_sample), float(d_sample)
    N = float(max(total_rows, n_sample))
    denom = n - f1 + f1 * n / N
    est = n * d / max(denom, 1.0)
    return float(min(max(est, d), N))


def analyze_column(arr: np.ndarray, valid: np.ndarray | None,
                   total_rows: int, kind: T.Kind,
                   rng: np.random.Generator) -> ColumnStats:
    st = ColumnStats()
    n = len(arr)
    if n == 0:
        return st
    if valid is not None:
        st.null_frac = float(1.0 - valid.mean())
        vals = arr[valid]
    else:
        vals = arr
    if len(vals) == 0:
        return st
    if kind in (T.Kind.INT32, T.Kind.INT64, T.Kind.DECIMAL, T.Kind.DATE,
                T.Kind.FLOAT64, T.Kind.BOOL):
        st.min = float(np.min(vals))
        st.max = float(np.max(vals))
    # NDV + MCV from a uniform WITHOUT-replacement sample: Duj1 models a
    # true row sample — drawing with replacement manufactures duplicate
    # draws of unique values, deflating NDV ~40% at a 0.8 sampling rate
    # and mis-classifying primary keys as duplicate-capable join builds
    if len(vals) > SAMPLE_ROWS:
        sample = vals[rng.choice(len(vals), SAMPLE_ROWS, replace=False)]
    else:
        sample = vals
    uniq, counts = np.unique(sample, return_counts=True)
    f1 = int((counts == 1).sum())
    live_total = int(total_rows * (1.0 - st.null_frac))
    st.ndv = _haas_stokes(len(sample), len(uniq), f1, live_total)
    # MCVs only when the sample suggests real skew concentration
    if len(uniq) <= 100:
        frac = counts / counts.sum()
        order = np.argsort(-counts)[:25]
        st.mcv = [(float(uniq[i]), float(frac[i])) for i in order]
    # equi-depth histogram for range selectivity on orderable columns;
    # skipped when the MCV list already describes the whole domain
    if kind in (T.Kind.INT32, T.Kind.INT64, T.Kind.DECIMAL, T.Kind.DATE,
                T.Kind.FLOAT64) and len(uniq) > 2:
        bounds = np.quantile(
            sample, np.linspace(0.0, 1.0, HIST_BUCKETS + 1))
        st.hist = [float(b) for b in bounds]
    return st


def table_fingerprint(snap: dict, schema) -> str:
    """Stable hash of a table's manifest entries (all storage children) —
    equal fingerprints mean the on-disk data is unchanged since analyze."""
    import hashlib
    import json

    tables = snap.get("tables", {})
    ent = {s: tables.get(s) for s in schema.storage_tables()}
    return hashlib.sha1(
        json.dumps(ent, sort_keys=True, default=str).encode()).hexdigest()[:16]


def analyze_table(store, schema, snapshot=None) -> TableStats:
    """One ANALYZE pass over a table: full min/max/null (vectorized),
    sampled NDV/MCV, per column."""
    snap = snapshot or store.manifest.snapshot()
    ts = TableStats(version=snap.get("version", 0),
                    fingerprint=table_fingerprint(snap, schema))
    nseg = schema.policy.numsegments
    rng = np.random.default_rng(0xA7A1)
    per_col: dict[str, list] = {c.name: [] for c in schema.columns}
    per_col_valid: dict[str, list] = {c.name: [] for c in schema.columns}
    total = 0
    # partitioned tables: stats aggregate over the child storage tables
    # (one logical relation, like pg_statistic on the partition root)
    for storage in schema.storage_tables():
        for seg in range(nseg):
            cols, valids, n = store.read_segment(storage, seg, None, snap)
            total += n
            for c in schema.columns:
                per_col[c.name].append(cols[c.name])
                v = valids.get(c.name)
                per_col_valid[c.name].append(
                    v if v is not None else np.ones(n, dtype=bool))
    from greengage_tpu.catalog.schema import PolicyKind

    if schema.policy.kind is PolicyKind.REPLICATED and nseg > 0:
        # identical copy on every segment: one copy is the table
        total //= nseg
        for c in schema.columns:
            per_col[c.name] = per_col[c.name][:1]
            per_col_valid[c.name] = per_col_valid[c.name][:1]
    ts.rows = total
    for c in schema.columns:
        if c.type.kind is T.Kind.TEXT and c.encoding == "raw":
            # raw columns carry surrogates on the scan path: no NDV/MCV
            # (their predicates are host-evaluated anyway)
            ts.columns[c.name] = ColumnStats()
            continue
        arr = np.concatenate(per_col[c.name]) if per_col[c.name] else np.empty(0)
        valid = np.concatenate(per_col_valid[c.name]) if per_col_valid[c.name] else None
        if valid is not None and valid.all():
            valid = None
        ts.columns[c.name] = analyze_column(arr, valid, total, c.type.kind, rng)
    return ts
