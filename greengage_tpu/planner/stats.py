"""Table statistics: ANALYZE + estimators — the pg_statistic / ORCA
statistics calculus analog.

Reference parity: ANALYZE's sample-based collection (the reference gathers
NDV/MCV/histograms into pg_statistic; ORCA consumes them through
libnaucrates' statistics objects, src/backend/gporca/libnaucrates/src/
statistics/). We collect, per column: exact min/max/null fraction (one
vectorized pass) and sample-based NDV using the Haas-Stokes (Duj1)
estimator PostgreSQL uses in analyze.c. MCVs are kept for low-cardinality
columns so equality selectivity on skewed columns is grounded.

Stats feed: filter selectivities, GROUP BY cardinality (est_groups),
join output cardinality, and motion/agg capacity sizing — where round 1
used constants (planner/cost.py), which cost a full XLA recompile per
mis-estimate via the overflow-tier retry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
import hashlib
import json

import numpy as np

from greengage_tpu import types as T

SAMPLE_ROWS = 240_000   # ~ the reference's default_statistics_target regime


HIST_BUCKETS = 32   # equi-depth buckets per numeric/date column


@dataclass
class ColumnStats:
    ndv: float = 0.0            # estimated distinct values (excl. NULL)
    null_frac: float = 0.0
    min: float | None = None    # storage-encoded (dates=days, decimals=scaled)
    max: float | None = None
    mcv: list = field(default_factory=list)     # [(encoded value, fraction)]
    # equi-depth histogram: HIST_BUCKETS+1 boundary values (sample
    # quantiles), each bucket holding ~1/HIST_BUCKETS of the non-null
    # mass — the pg_statistic histogram_bounds / CHistogram bucket
    # calculus analog. Range selectivity reads bucket positions instead of
    # linearly interpolating [min, max], which is wrong on any skewed
    # distribution (and every mis-estimate here costs an XLA recompile
    # tier).
    hist: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"ndv": self.ndv, "null_frac": self.null_frac,
                "min": self.min, "max": self.max, "mcv": self.mcv,
                "hist": self.hist}

    @staticmethod
    def from_dict(d: dict) -> "ColumnStats":
        return ColumnStats(d.get("ndv", 0.0), d.get("null_frac", 0.0),
                           d.get("min"), d.get("max"),
                           [tuple(x) for x in d.get("mcv", [])],
                           list(d.get("hist", [])))


@dataclass
class TableStats:
    rows: int = 0
    version: int = -1           # manifest version when analyzed
    columns: dict = field(default_factory=dict)   # name -> ColumnStats
    # content hash of the table's manifest entry at analyze time: the
    # `gg analyzedb` incremental gate (analyzedb's mtime+state tracking
    # analog) — unchanged hash = stats still describe the data
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {"rows": self.rows, "version": self.version,
                "fingerprint": self.fingerprint,
                "columns": {n: c.to_dict() for n, c in self.columns.items()}}

    @staticmethod
    def from_dict(d: dict) -> "TableStats":
        return TableStats(d.get("rows", 0), d.get("version", -1),
                          {n: ColumnStats.from_dict(c)
                           for n, c in d.get("columns", {}).items()},
                          d.get("fingerprint", ""))


def _haas_stokes(n_sample: int, d_sample: int, f1: int, total_rows: int) -> float:
    """Duj1 NDV estimator (what analyze.c uses): scale the sample's distinct
    count by how many singletons it saw. All-distinct samples extrapolate to
    the full table; no-singleton samples are near-complete domains."""
    if n_sample == 0:
        return 0.0
    if d_sample >= n_sample:
        return float(total_rows)
    if f1 == 0:
        return float(d_sample)
    n, d = float(n_sample), float(d_sample)
    N = float(max(total_rows, n_sample))
    denom = n - f1 + f1 * n / N
    est = n * d / max(denom, 1.0)
    return float(min(max(est, d), N))


def analyze_column(arr: np.ndarray, valid: np.ndarray | None,
                   total_rows: int, kind: T.Kind,
                   rng: np.random.Generator) -> ColumnStats:
    st = ColumnStats()
    n = len(arr)
    if n == 0:
        return st
    if valid is not None:
        st.null_frac = float(1.0 - valid.mean())
        vals = arr[valid]
    else:
        vals = arr
    if len(vals) == 0:
        return st
    if kind in (T.Kind.INT32, T.Kind.INT64, T.Kind.DECIMAL, T.Kind.DATE,
                T.Kind.FLOAT64, T.Kind.BOOL):
        st.min = float(np.min(vals))
        st.max = float(np.max(vals))
    # NDV + MCV from a uniform WITHOUT-replacement sample: Duj1 models a
    # true row sample — drawing with replacement manufactures duplicate
    # draws of unique values, deflating NDV ~40% at a 0.8 sampling rate
    # and mis-classifying primary keys as duplicate-capable join builds
    if len(vals) > SAMPLE_ROWS:
        sample = vals[rng.choice(len(vals), SAMPLE_ROWS, replace=False)]
    else:
        sample = vals
    uniq, counts = np.unique(sample, return_counts=True)
    f1 = int((counts == 1).sum())
    live_total = int(total_rows * (1.0 - st.null_frac))
    st.ndv = _haas_stokes(len(sample), len(uniq), f1, live_total)
    # MCVs only when the sample suggests real skew concentration
    if len(uniq) <= 100:
        frac = counts / counts.sum()
        order = np.argsort(-counts)[:25]
        st.mcv = [(float(uniq[i]), float(frac[i])) for i in order]
    # equi-depth histogram for range selectivity on orderable columns;
    # skipped when the MCV list already describes the whole domain
    if kind in (T.Kind.INT32, T.Kind.INT64, T.Kind.DECIMAL, T.Kind.DATE,
                T.Kind.FLOAT64) and len(uniq) > 2:
        bounds = np.quantile(
            sample, np.linspace(0.0, 1.0, HIST_BUCKETS + 1))
        st.hist = [float(b) for b in bounds]
    return st


def _hist_mass(hist: list, a: float, b: float,
               skip_points: bool = False) -> float:
    """Fraction of a column's non-null mass inside [a, b), reading the
    equi-depth histogram piecewise-linearly (each bucket = 1/B mass).
    ``skip_points`` excludes zero-width buckets (point masses a caller
    accounts separately)."""
    B = len(hist) - 1
    if B < 1 or b <= hist[0] or a >= hist[-1]:
        return 0.0
    acc = 0.0
    for i in range(B):
        lo, hi = hist[i], hist[i + 1]
        if hi <= a or lo >= b:
            continue
        if hi <= lo:
            # zero-width bucket (heavy duplicate at a boundary)
            if not skip_points and a <= lo < b:
                acc += 1.0 / B
            continue
        ov = (min(hi, b) - max(lo, a)) / (hi - lo)
        acc += max(min(ov, 1.0), 0.0) / B
    return acc


def _point_masses(hist: list) -> dict:
    """Heavy single values an equi-depth histogram exposes as zero-width
    buckets: any value holding >= 1/B of the mass appears as repeated
    boundaries — a free MCV list for skew the sampler's MCV gate (<=100
    uniques) missed."""
    B = len(hist) - 1
    pm: dict = {}
    for i in range(B):
        if hist[i + 1] <= hist[i]:
            pm[hist[i]] = pm.get(hist[i], 0.0) + 1.0 / B
    return pm


def join_selectivity(ls: ColumnStats, rs: ColumnStats,
                     kinds=None) -> float | None:
    """Equi-join selectivity per NON-NULL row pair via MCV x MCV exact
    matching + aligned-histogram remainder — the CJoinStatsProcessor role
    (/root/reference/src/backend/gporca/libnaucrates/src/statistics/
    CJoinStatsProcessor.cpp:1) in piecewise-uniform form:

        est_rows = |L|(1-nf_l) * |R|(1-nf_r) * sel

    The MCV part captures skew exactly where both sides kept frequencies;
    the histogram part distributes the residual NDV proportionally to
    bucket mass, so partially-overlapping key ranges (the case NDV
    division overestimates by orders of magnitude) contribute only their
    overlap. None when neither MCV nor histogram evidence exists (caller
    falls back to 1/max(ndv)). Note: the sample histogram includes MCV
    rows (the reference excludes them); the residual-mass scaling keeps
    the double-count second-order."""
    if ls is None or rs is None:
        return None
    # only VALUE-comparable storage encodings may align across tables:
    # TEXT stats hold per-column dictionary codes (code 3 is a different
    # string in each table) and DECIMAL values are scale-encoded — both
    # fall back to NDV division, which is encoding-independent. BOTH
    # sides must be plainly-encoded (a single kind, or an int/int pair)
    if kinds is not None:
        kl, kr = kinds if isinstance(kinds, tuple) else (kinds, kinds)
        ints = (T.Kind.INT32, T.Kind.INT64)
        ok = (kl in ints and kr in ints) or (
            kl == kr and kl in (T.Kind.DATE, T.Kind.FLOAT64))
        if not ok:
            return None
    have_hist = len(ls.hist) > 1 and len(rs.hist) > 1
    # sampled MCVs, augmented with the point masses zero-width histogram
    # buckets expose (explicit MCV frequencies win on overlap)
    ml = {**(_point_masses(ls.hist) if have_hist else {}), **dict(ls.mcv)}
    mr = {**(_point_masses(rs.hist) if have_hist else {}), **dict(rs.mcv)}
    if not have_hist and not (ml and mr):
        return None
    sel = 0.0
    for v, fl in ml.items():
        fr = mr.get(v)
        if fr is not None:
            sel += fl * fr
    rem_l = max(1.0 - sum(ml.values()), 0.0)
    rem_r = max(1.0 - sum(mr.values()), 0.0)
    ndv_l = max(ls.ndv - len(ml), 1.0)
    ndv_r = max(rs.ndv - len(mr), 1.0)
    # one-sided skew: an MCV/point value absent from the OTHER side's
    # list still matches its histogram mass at that side's average
    # residual per-value frequency (PG's mcv-vs-histogram cross term) —
    # without this a skewed FK joining a unique PK loses the heavy
    # value's entire contribution
    def _in_range(v, st):
        return len(st.hist) > 1 and st.hist[0] <= v <= st.hist[-1]

    for v, fl in ml.items():
        if v not in mr and _in_range(v, rs):
            sel += fl * (rem_r / ndv_r)
    for v, fr in mr.items():
        if v not in ml and _in_range(v, ls):
            sel += fr * (rem_l / ndv_l)
    if rem_l <= 1e-9 or rem_r <= 1e-9:
        return max(sel, 1e-12)
    if have_hist:
        lo = max(ls.hist[0], rs.hist[0])
        hi = min(ls.hist[-1], rs.hist[-1])
        if hi > lo:
            bounds = sorted(b for b in set(ls.hist) | set(rs.hist)
                            if lo <= b <= hi)
            # residual (non-point) masses, renormalized so they sum to 1
            # over each side's residual domain
            tot_l = max(1.0 - sum(_point_masses(ls.hist).values()), 1e-9)
            tot_r = max(1.0 - sum(_point_masses(rs.hist).values()), 1e-9)
            acc = 0.0
            for a, b in zip(bounds, bounds[1:]):
                mli = _hist_mass(ls.hist, a, b, skip_points=True) / tot_l
                mri = _hist_mass(rs.hist, a, b, skip_points=True) / tot_r
                if mli <= 0.0 or mri <= 0.0:
                    continue
                acc += mli * mri / max(ndv_l * mli, ndv_r * mri, 1.0)
            # single-point overlap (hi==lo) or no interior falls through
            sel += rem_l * rem_r * acc
        # disjoint histogram ranges: the remainder truly contributes 0
    else:
        sel += rem_l * rem_r / max(ndv_l, ndv_r)
    return max(sel, 1e-12)


def table_fingerprint(snap: dict, schema) -> str:
    """Stable hash of a table's manifest entries (all storage children) —
    equal fingerprints mean the on-disk data is unchanged since analyze."""

    tables = snap.get("tables", {})
    ent = {s: tables.get(s) for s in schema.storage_tables()}
    return hashlib.sha1(
        json.dumps(ent, sort_keys=True, default=str).encode()).hexdigest()[:16]


def analyze_table(store, schema, snapshot=None) -> TableStats:
    """One ANALYZE pass over a table: full min/max/null (vectorized),
    sampled NDV/MCV, per column."""
    snap = snapshot or store.manifest.snapshot()
    ts = TableStats(version=snap.get("version", 0),
                    fingerprint=table_fingerprint(snap, schema))
    nseg = schema.policy.numsegments
    rng = np.random.default_rng(0xA7A1)
    per_col: dict[str, list] = {c.name: [] for c in schema.columns}
    per_col_valid: dict[str, list] = {c.name: [] for c in schema.columns}
    total = 0
    # partitioned tables: stats aggregate over the child storage tables
    # (one logical relation, like pg_statistic on the partition root)
    for storage in schema.storage_tables():
        for seg in range(nseg):
            cols, valids, n = store.read_segment(storage, seg, None, snap)
            total += n
            for c in schema.columns:
                per_col[c.name].append(cols[c.name])
                v = valids.get(c.name)
                per_col_valid[c.name].append(
                    v if v is not None else np.ones(n, dtype=bool))
    from greengage_tpu.catalog.schema import PolicyKind

    if schema.policy.kind is PolicyKind.REPLICATED and nseg > 0:
        # identical copy on every segment: one copy is the table
        total //= nseg
        for c in schema.columns:
            per_col[c.name] = per_col[c.name][:1]
            per_col_valid[c.name] = per_col_valid[c.name][:1]
    ts.rows = total
    for c in schema.columns:
        if c.type.kind is T.Kind.TEXT and c.encoding == "raw":
            # raw columns carry surrogates on the scan path: no NDV/MCV
            # (their predicates are host-evaluated anyway)
            ts.columns[c.name] = ColumnStats()
            continue
        arr = np.concatenate(per_col[c.name]) if per_col[c.name] else np.empty(0)
        valid = np.concatenate(per_col_valid[c.name]) if per_col_valid[c.name] else None
        if valid is not None and valid.all():
            valid = None
        ts.columns[c.name] = analyze_column(arr, valid, total, c.type.kind, rng)
    return ts
