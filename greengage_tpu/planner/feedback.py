"""Feedback-driven cost calibration — the closed measurement loop.

Six perf rounds built ground truth the planner never read: per-node
actual rows (instrumented runs + the always-on filter counters), the
AOT executable's measured ``memory_analysis`` bytes, and the capacity
hints the device reports after every successful dispatch. This module
is the store that feeds it all back (the ROADMAP item-2 "compounding
layer"; the same lesson Theseus draws for distributed accelerators:
static cost models drift, and on accelerators a 3x-wrong cardinality is
a wrong motion plan, a wrong capacity bucket, and a wrong admission
verdict all at once).

Three feedback surfaces, one store:

  * **row-scale corrections** keyed by a *structural node digest*
    (value-stable across processes: same SQL -> same bind -> same
    digest): after each execution the session reconciles per-node
    actual rows against the planner's ``est_rows`` and maintains a
    bounded EWMA of the log-ratio per digest. A correction is only
    *applied* (promoted) when it drifts past the hysteresis band
    (``cost_feedback_hysteresis``), so estimate noise never re-plans a
    stable shape. Applied scales multiply ``est_rows`` during planning
    — this is also what supersedes a generic plan's ``ParamRef
    .est_value`` seed: the first bind's literals seed the selectivity,
    observed traffic corrects it.
  * **measured executable bytes** keyed by statement shape (the
    executor's cache key): admission, the runaway ledger, and the
    batch-width bound prefer the measured footprint the moment a shape
    is warm — and, because the store persists, across process restarts
    too (``mem_est_error_pct`` collapses toward 0 on the second
    execution of any shape).
  * **capacity hints** ({stable node ordinal -> pow2 capacity}): the
    device-reported exact counts outlive the process, so a restarted
    coordinator compiles right-sized programs on first touch.

Every promotion bumps the store generation; ``version_for`` joins the
bound-plan cache key (exec/session._cached_plan), so a re-calibrated
shape re-plans instead of serving the stale plan. Multihost lockstep:
only the coordinator reconciles; it ships its applied scales + the
generation with every statement broadcast and workers ``adopt()`` them
before planning, so both sides plan from identical numbers and the
plan-hash verification holds. The store persists to
``<cluster>/feedback.json`` beside the catalog (coordinator only,
atomic rename) and ships with the PR-19 standby meta sync.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import tempfile
import threading

from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters

# bounds on one digest's applied row-scale correction: a clamped scale
# still flips every motion/admission decision a 64x error could, while
# an unbounded one would let a single garbage observation poison a shape
SCALE_MIN, SCALE_MAX = 1.0 / 64.0, 64.0
# EWMA smoothing for the log-ratio; the FIRST observation initializes
# the average fully, so a cold 3x-wrong shape corrects after one run
EWMA_ALPHA = 0.5
MAX_DIGESTS = 1024      # LRU-ish prune bound on tracked node digests
MAX_SHAPES = 512        # and on tracked statement shapes


# ---- structural node digests -----------------------------------------
def node_digest(node) -> str:
    """Value-stable structural digest of an estimating plan node.
    Pass-through nodes (Motion/Project/Sort/Limit/Window) are
    transparent, so the same filter learns one correction whether or
    not a projection sits between it and its scan. Binder/paramize are
    deterministic, so the digest is identical across processes and
    restarts for the same statement shape — param placeholders carry no
    values (their ``est_value`` seed is repr-excluded), which is exactly
    what lets observed traffic supersede the seed."""
    d = getattr(node, "_fb_digest", None)
    if d is None:
        d = hashlib.sha1(_sig(node).encode()).hexdigest()[:16]
        try:
            node._fb_digest = d
        except Exception:
            pass
    return d


# value-placeholder normalization: the plan cache hoists literals into
# Param slots, so the SAME statement carries Literal(value=...) when
# planned directly (EXPLAIN, unparameterizable shapes) and Param(slot=N)
# when served generically. Both forms — and successive bind values — must
# learn ONE correction per shape, so every comparable value collapses to
# '?' in the digest signature (the ParamRef.est_value supersession rule:
# the first bind seeds the estimate, observed traffic corrects it)
_VALUE_RE = re.compile(
    r"(?:Literal\(value=.*?, type=SqlType\([^)]*\)\)"
    r"|Param\(slot=\d+, type=SqlType\([^)]*\)\))")


def _norm(r: str) -> str:
    return _VALUE_RE.sub("?", r)


def _sig(node) -> str:
    kind = type(node).__name__
    if kind == "Scan":
        return f"scan({node.table})"
    if kind == "Filter":
        return f"filter({_norm(repr(node.predicate))})<{_sig(node.child)}"
    if kind == "Join":
        keys = ",".join(f"{lk!r}={rk!r}" for lk, rk in
                        zip(node.left_keys, node.right_keys))
        return (f"join({node.kind};{keys};{_norm(repr(node.residual))})"
                f"<{_sig(node.left)}|{_sig(node.right)}")
    if kind == "Aggregate":
        keys = ",".join(repr(e) for _, e in node.group_keys)
        aggs = ",".join(_norm(repr(a)) for _, a in node.aggs)
        return f"agg({keys};{aggs})<{_sig(node.child)}"
    if kind == "Union":
        return "union<" + "|".join(_sig(c) for c in node.inputs)
    child = getattr(node, "child", None)
    if child is not None:
        return _sig(child)         # pass-through wrapper
    return kind.lower()


def shape_key(key_sig: str) -> str:
    return hashlib.sha1(key_sig.encode()).hexdigest()[:16]


class FeedbackStore:
    """Per-cluster feedback state. Thread-safe (server threads reconcile
    and plan concurrently); all mutation under one lock, reads of the
    applied-scale map take the same lock and copy out."""

    def __init__(self, path: str | None = None, persist: bool = True,
                 settings=None):
        self.path = path
        self.persist = persist
        self.settings = settings
        self._mu = threading.Lock()
        self.gen = 0              # bumped per promotion; the plan-cache
        self._adopt_gen = 0       # calibration version workers adopt
        # digest -> {"scale": applied, "lr": ewma log-ratio, "n": obs,
        #            "est": last est, "actual": last actual}
        self.digests: dict[str, dict] = {}
        # shape key -> {"ver": gen at last promotion touching it,
        #   "digests": [...], "sql": label, "runs": n,
        #   "rows_est": float, "rows_actual": float,
        #   "est_bytes": int, "measured_bytes": int, "caps": {nid: cap}}
        self.shapes: dict[str, dict] = {}
        self._load()

    # ---- persistence (atomic, coordinator-only) ----------------------
    def _load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                raw = json.load(f)
            self.gen = int(raw.get("gen", 0))
            self.digests = {str(k): dict(v)
                            for k, v in (raw.get("digests") or {}).items()}
            self.shapes = {str(k): dict(v)
                           for k, v in (raw.get("shapes") or {}).items()}
            if self.gen:
                # a restarted process must expose the loaded calibration
                # generation, not 0, to scrapers
                counters.set("calibration_version", self.gen)
        except (OSError, ValueError, TypeError):
            # an unreadable store must never block startup: feedback is
            # an optimization layer, cold estimates still work
            self.gen, self.digests, self.shapes = 0, {}, {}

    def save(self) -> None:
        if not self.persist or not self.path:
            return
        with self._mu:
            payload = {"gen": self.gen, "digests": self.digests,
                       "shapes": self.shapes}
            try:
                d = os.path.dirname(self.path) or "."
                fd, tmp = tempfile.mkstemp(dir=d, prefix=".feedback-")
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.path)
            except OSError:
                pass              # best-effort; next promotion retries

    # ---- planner read path -------------------------------------------
    def scale_for(self, digest: str) -> float:
        with self._mu:
            rec = self.digests.get(digest)
            return float(rec["scale"]) if rec else 1.0

    def corrected_rows(self, node) -> float:
        s = self.scale_for(node_digest(node))
        if s == 1.0:
            return node.est_rows
        return max(float(node.est_rows) * s, 1e-6)

    def version_for(self, key_sig: str) -> int:
        """The calibration version joining the bound-plan cache key: a
        promotion touching any digest this shape uses bumps it, so the
        next execution re-plans with the corrected estimates."""
        with self._mu:
            sh = self.shapes.get(shape_key(key_sig))
            return max(int(sh["ver"]) if sh else 0, self._adopt_gen)

    def note_shape(self, key_sig: str, planned) -> None:
        """Register which digests a freshly planned shape depends on
        (the reverse index promotions walk to bump shape versions)."""
        ds: list[str] = []
        _walk_estimating(planned, lambda n: ds.append(node_digest(n)))
        with self._mu:
            sh = self.shapes.setdefault(
                shape_key(key_sig),
                {"ver": 0, "runs": 0, "sql": key_sig[:160]})
            sh["digests"] = ds
            self._prune_locked()

    # ---- execution-side write path -----------------------------------
    def reconcile(self, key_sig: str, planned, rows_out: int,
                  node_rows: dict | None,
                  measured_bytes: int | None = None,
                  est_bytes: int | None = None) -> int:
        """Reconcile one execution's actuals against the planned
        estimates; promote drifted corrections (hysteresis-gated) and
        bump the generation + every dependent shape's version. Returns
        the number of promotions. Deterministic: identical observations
        produce identical store states on every process."""
        obs: list[tuple[str, float, float]] = []    # (digest, est, actual)
        seen: set[str] = set()
        if node_rows:
            # per-node actuals: instrumented runs cover every node, the
            # always-on filter counters cover Filter nodes on every run
            def take(n):
                rows = node_rows.get(id(n))
                if rows is not None:
                    d = node_digest(n)
                    seen.add(d)
                    obs.append((d, float(n.est_rows), float(rows)))
            _walk_estimating(planned, take)
        # root attribution: rows_out is exact on every run; walk through
        # row-preserving nodes and credit the topmost estimating node
        # not already directly observed this run
        top = _root_estimating(planned)
        if top is not None and rows_out >= 0:
            d = node_digest(top)
            if d not in seen:
                obs.append((d, float(top.est_rows), float(rows_out)))
        hyst = max(float(getattr(self.settings, "cost_feedback_hysteresis",
                                 1.5) or 1.5), 1.0 + 1e-9)
        promoted = 0
        with self._mu:
            sk = shape_key(key_sig)
            sh = self.shapes.setdefault(
                sk, {"ver": 0, "runs": 0, "sql": key_sig[:160],
                     "digests": []})
            sh["runs"] = int(sh.get("runs", 0)) + 1
            sh["rows_est"] = float(getattr(planned, "est_rows", 0.0))
            sh["rows_actual"] = float(rows_out)
            if est_bytes is not None:
                sh["est_bytes"] = int(est_bytes)
            if measured_bytes is not None and measured_bytes > 0:
                sh["measured_bytes"] = int(measured_bytes)
            touched: set[str] = set()
            for d, est, actual in obs:
                rec = self.digests.setdefault(
                    d, {"scale": 1.0, "lr": 0.0, "n": 0})
                # the planned est already carries the APPLIED scale (a
                # promotion re-plans the shape), so the observation's
                # residual ratio composes onto it: the EWMA tracks the
                # implied TOTAL scale in log space. Steady state: actual
                # ~= est -> the ewma converges to log(scale) exactly and
                # the hysteresis gate never re-fires on a settled shape.
                ratio = max(actual, 1e-6) / max(est, 1e-6)
                lr = math.log(max(rec["scale"], SCALE_MIN)) \
                    + math.log(ratio)
                rec["lr"] = (lr if rec["n"] == 0
                             else (1 - EWMA_ALPHA) * rec["lr"]
                             + EWMA_ALPHA * lr)
                rec["n"] = int(rec["n"]) + 1
                rec["est"], rec["actual"] = est, actual
                cand = min(max(math.exp(rec["lr"]), SCALE_MIN), SCALE_MAX)
                # hysteresis: promote only when the candidate drifted
                # past the band around the APPLIED scale — noise inside
                # the band never invalidates cached plans
                if abs(math.log(cand / rec["scale"])) > math.log(hyst):
                    if faults.check("feedback_apply"):
                        continue      # injected skip: calibration stays
                        # pending (checkperf --apply commits it)
                    rec["scale"] = cand
                    touched.add(d)
                    promoted += 1
            if promoted:
                self.gen += 1
                counters.inc("feedback_applied_total", promoted)
                counters.set("calibration_version", self.gen)
                for shp in self.shapes.values():
                    if touched.intersection(shp.get("digests") or ()):
                        shp["ver"] = self.gen
            self._prune_locked()
        if promoted:
            self.save()
        return promoted

    # ---- measured bytes / capacity hints ------------------------------
    def note_measured(self, exec_key: str, measured_total: int,
                      est_dev: int) -> None:
        with self._mu:
            sh = self.shapes.setdefault(
                shape_key(exec_key),
                {"ver": 0, "runs": 0, "sql": exec_key[:160],
                 "digests": []})
            sh["measured_bytes"] = int(measured_total)
            sh["est_dev_bytes"] = int(est_dev)
            self._prune_locked()

    def measured_bytes(self, exec_key: str) -> int | None:
        with self._mu:
            sh = self.shapes.get(shape_key(exec_key))
            mb = sh.get("measured_bytes") if sh else None
            return int(mb) if mb else None

    def note_caps(self, exec_key: str, caps: dict) -> None:
        if not caps:
            return
        with self._mu:
            sh = self.shapes.setdefault(
                shape_key(exec_key),
                {"ver": 0, "runs": 0, "sql": exec_key[:160],
                 "digests": []})
            sh["caps"] = {str(k): int(v) for k, v in caps.items()}
            self._prune_locked()

    def caps(self, exec_key: str) -> dict:
        with self._mu:
            sh = self.shapes.get(shape_key(exec_key))
            return ({int(k): int(v) for k, v in sh["caps"].items()}
                    if sh and sh.get("caps") else {})

    # ---- multihost lockstep (coordinator ships, workers adopt) --------
    def wire_payload(self) -> dict:
        with self._mu:
            return {"gen": self.gen,
                    "scales": {d: r["scale"] for d, r in
                               self.digests.items()
                               if r.get("scale", 1.0) != 1.0}}

    def adopt(self, payload: dict | None) -> None:
        """Worker side: install the coordinator's applied scales before
        planning. Scales travel as JSON floats (exact round-trip), so
        both sides plan from identical numbers and the plan hash
        matches."""
        if not payload:
            return
        with self._mu:
            for d, s in (payload.get("scales") or {}).items():
                rec = self.digests.setdefault(
                    str(d), {"scale": 1.0, "lr": 0.0, "n": 0})
                rec["scale"] = float(s)
                rec["lr"] = math.log(max(float(s), 1e-9))
            gen = int(payload.get("gen", 0))
            if gen > self._adopt_gen:
                self._adopt_gen = gen
            counters.set("calibration_version",
                         max(self.gen, self._adopt_gen))

    # ---- checkperf surface -------------------------------------------
    def report(self) -> dict:
        """Per-shape est-vs-actual error (rows + bytes) + the digest
        correction table — the `gg checkperf` feedback report."""
        with self._mu:
            shapes = []
            for sk, sh in self.shapes.items():
                row = {"shape": sk, "sql": sh.get("sql", ""),
                       "runs": int(sh.get("runs", 0)),
                       "ver": int(sh.get("ver", 0))}
                re_, ra = sh.get("rows_est"), sh.get("rows_actual")
                if re_ is not None and ra is not None:
                    row["rows_est"] = re_
                    row["rows_actual"] = ra
                    row["rows_err_pct"] = round(
                        100.0 * (re_ - ra) / max(ra, 1e-9), 1)
                eb, mb = sh.get("est_dev_bytes") or sh.get("est_bytes"), \
                    sh.get("measured_bytes")
                if eb and mb:
                    row["est_bytes"] = int(eb)
                    row["measured_bytes"] = int(mb)
                    row["bytes_err_pct"] = round(
                        100.0 * (eb - mb) / max(mb, 1), 1)
                shapes.append(row)
            pending = sum(
                1 for r in self.digests.values()
                if abs(math.log(
                    min(max(math.exp(r.get("lr", 0.0)), SCALE_MIN),
                        SCALE_MAX) / r.get("scale", 1.0))) > 1e-9)
            return {"gen": self.gen, "digests": len(self.digests),
                    "pending": pending, "shapes": shapes,
                    "scales": {d: round(r["scale"], 4)
                               for d, r in self.digests.items()
                               if r.get("scale", 1.0) != 1.0}}

    def apply_pending(self) -> int:
        """`gg checkperf --apply`: commit every candidate correction
        regardless of the hysteresis band."""
        applied = 0
        with self._mu:
            touched = set()
            for d, rec in self.digests.items():
                cand = min(max(math.exp(rec.get("lr", 0.0)), SCALE_MIN),
                           SCALE_MAX)
                if abs(math.log(cand / rec.get("scale", 1.0))) > 1e-9:
                    rec["scale"] = cand
                    touched.add(d)
                    applied += 1
            if applied:
                self.gen += 1
                counters.inc("feedback_applied_total", applied)
                counters.set("calibration_version", self.gen)
                for sh in self.shapes.values():
                    if touched.intersection(sh.get("digests") or ()):
                        sh["ver"] = self.gen
        if applied:
            self.save()
        return applied

    def reset(self) -> None:
        """`gg checkperf --reset`: clear all learned corrections; the
        generation still bumps so cached corrected plans re-plan."""
        with self._mu:
            self.digests.clear()
            self.shapes.clear()
            self.gen += 1
            counters.set("calibration_version", self.gen)
        self.save()

    # ---- internal -----------------------------------------------------
    def _prune_locked(self) -> None:
        # bounded state: drop the least-run shapes / lowest-signal
        # digests (deterministic order so multihost stores stay equal)
        while len(self.shapes) > MAX_SHAPES:
            victim = min(self.shapes.items(),
                         key=lambda kv: (kv[1].get("runs", 0), kv[0]))[0]
            del self.shapes[victim]
        while len(self.digests) > MAX_DIGESTS:
            victim = min(self.digests.items(),
                         key=lambda kv: (kv[1].get("n", 0), kv[0]))[0]
            del self.digests[victim]


def _walk_estimating(node, fn) -> None:
    kind = type(node).__name__
    if kind in ("Filter", "Join", "Aggregate"):
        fn(node)
    for c in getattr(node, "children", ()) or ():
        _walk_estimating(c, fn)


def _root_estimating(node):
    """Topmost Filter/Join/Aggregate reachable from the root through
    row-preserving nodes — the node the exact ``rows_out`` observation
    can be attributed to. Limit truncates and Broadcast replicates, so
    both stop the walk."""
    while node is not None:
        kind = type(node).__name__
        if kind in ("Filter", "Join", "Aggregate"):
            return node
        if kind == "Motion":
            if getattr(getattr(node, "kind", None), "name", "") \
                    == "BROADCAST":
                return None
            node = node.child
            continue
        if kind in ("Project", "Sort", "Window"):
            node = node.child
            continue
        return None
    return None
