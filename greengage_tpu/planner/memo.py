"""Cascades-lite memo optimizer — the ORCA analog.

Reference parity: the GPORCA stack (src/backend/gporca): CMemo groups +
exploration/implementation rules (libgpopt/src/engine/CEngine.cpp:1678),
distribution-property enforcement (CDistributionSpecHashed), and the
statistics calculus feeding costs (libnaucrates/src/statistics/). The
redesign collapses that machinery to the part that changes plans on a
TPU mesh: **global join-order search over bushy trees with
distribution-property-aware dynamic programming**, costed in bytes
moved over ICI (planner/cost.py's model) — motion is the dominant cost
a join order can change on this architecture.

Shape of the search (DPccp-flavored over the equi-edge graph):

  group  = bitmask of base relations (the CMemo group analog)
  state  = {distribution property -> cheapest (cost, tree, rows)}
           where a property is the tuple of column ids the result is
           hash-distributed on, or "repl" for replicated inputs
  expand = for each connected (subgraph, complement) split joined by at
           least one equi edge, try: colocated (no motion), redistribute
           one side, redistribute both, broadcast either side —
           exactly the cdbpath_motion_for_join menu, but costed
           *globally* so a cheap distribution below pays off above.

The winner is extracted as a nested tuple of relation indices, e.g.
``((0, 2), (1, 3))`` — a bushy tree the binder turns into Join nodes.
The fallback planner (optimizer=off) keeps its left-deep Selinger DP /
greedy order; both share the same cost constants, so EXPLAIN diffs
between the two are attributable to search scope, not cost-model drift.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from greengage_tpu.planner import cost as C

MAX_RELS = 10          # same bound as the left-deep DP (CJoinOrderDP caps too)
MAX_PROPS = 4          # distribution properties kept per group (pruning)

REPL = "repl"          # property: replicated everywhere (no motion to join)


@dataclass
class RelInfo:
    """One base relation (a filtered scan) entering the join search."""

    rows: float
    width: float                       # bytes/row estimate
    dist_cols: tuple = ()              # bound col ids it is hash-placed on
    replicated: bool = False


@dataclass
class EdgeInfo:
    """All equi-join conjuncts between two relations, merged."""

    a: int
    b: int
    pairs: list = field(default_factory=list)   # (a-side col id, b-side col id)
    sel: float = 1.0                            # product of 1/max(ndv, ndv)


@dataclass
class _Alt:
    cost: float
    tree: object          # int leaf | (tree, tree)
    rows: float
    width: float
    leaves: int           # bitmask of member relations


@dataclass
class AggInfo:
    """The GROUP BY sitting above the join search, so aggregation
    placement is optimized JOINTLY with join order — the CXformSplitGbAgg
    role (libgpopt/src/xforms/CXformSplitGbAgg.cpp): a final alternative
    already hash-distributed on the group keys finishes with a single
    motion-free aggregate, which can justify a join order that loses on
    join cost alone (VERDICT r3 #1/#3)."""

    group_cols: tuple      # bound col ids of the GROUP BY keys
    groups: float          # NDV-product estimate (uncapped; capped per alt)
    naggs: int


def agg_completion_cost(prop, rows: float, width: float, agg: AggInfo,
                        nseg: int) -> float:
    """Per-chip ns to finish ``agg`` over a join result with distribution
    property ``prop``: zero extra motion when the property covers the
    group keys (single-phase), otherwise the cheaper of two-phase
    (partial -> redistribute states -> final) and one-phase (redistribute
    raw rows -> single agg) — the same costed choice
    planner._plan_aggregate makes, evaluated here per join alternative."""
    nk = max(len(agg.group_cols), 1)
    na = max(agg.naggs, 1)
    groups = max(min(agg.groups, rows), 1.0)
    if prop and prop != REPL and set(prop) <= set(agg.group_cols):
        return C.agg_cost(rows, groups, nk, na, width, nseg)
    state_w = 8.0 * (nk + 2 * na)
    partial_rows = min(rows, groups * max(nseg, 1))
    two = (C.agg_cost(rows, groups, nk, na, width, nseg)
           + C.motion_cost("redistribute", partial_rows, state_w, nseg)
           + C.agg_cost(partial_rows, groups, nk, na, state_w, nseg))
    one = (C.motion_cost("redistribute", rows, width, nseg)
           + C.agg_cost(rows, groups, nk, na, width, nseg))
    return min(two, one)


def optimize(rels: list[RelInfo], edges: list[EdgeInfo], nseg: int,
             agg: AggInfo | None = None):
    """-> nested index tree minimizing total bytes moved + touched —
    including, when ``agg`` is given, the cost of completing the GROUP BY
    above the tree — or None when the search doesn't apply (too many
    rels, disconnected join graph, no edges)."""
    n = len(rels)
    if n < 2 or n > MAX_RELS or not edges:
        return None

    adj: dict[int, int] = {i: 0 for i in range(n)}      # idx -> neighbor mask
    edge_by_pair: dict[tuple, EdgeInfo] = {}
    for e in edges:
        adj[e.a] |= 1 << e.b
        adj[e.b] |= 1 << e.a
        edge_by_pair[(min(e.a, e.b), max(e.a, e.b))] = e

    full = (1 << n) - 1

    def connected(mask: int) -> bool:
        first = mask & -mask
        seen = first
        frontier = first
        while frontier:
            nxt = 0
            m = frontier
            while m:
                i = (m & -m).bit_length() - 1
                m &= m - 1
                nxt |= adj[i] & mask & ~seen
            seen |= nxt
            frontier = nxt
        return seen == mask

    if not connected(full):
        # cross-product components: let the fallback handle them
        return None

    def members(mask: int):
        m = mask
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            yield i

    # memo: mask -> {prop: _Alt}
    memo: dict[int, dict] = {}
    for i, r in enumerate(rels):
        prop = REPL if r.replicated else tuple(r.dist_cols)
        memo[1 << i] = {prop: _Alt(0.0, i, max(r.rows, 1.0), r.width, 1 << i)}

    for mask in range(3, full + 1):
        if mask.bit_count() < 2 or (mask & full) != mask or not connected(mask):
            continue
        state: dict = {}
        low = mask & -mask
        sub = (mask - 1) & mask
        while sub:
            other = mask ^ sub
            if (sub & low) and other:
                s1 = memo.get(sub)
                s2 = memo.get(other)
                if s1 and s2:
                    xe = _cross_edges(sub, other, members, edge_by_pair)
                    if xe:
                        _expand(state, s1, s2, sub, xe, nseg)
            sub = (sub - 1) & mask
        if state:
            best = sorted(state.items(), key=lambda kv: kv[1].cost)
            memo[mask] = dict(best[:MAX_PROPS])

    final = memo.get(full)
    if not final:
        return None
    if agg is None:
        return min(final.values(), key=lambda a: a.cost).tree
    return min(
        final.items(),
        key=lambda kv: kv[1].cost + agg_completion_cost(
            kv[0], kv[1].rows, kv[1].width, agg, nseg))[1].tree


def _cross_edges(m1: int, m2: int, members, edge_by_pair):
    out = []
    for i in members(m1):
        for j in members(m2):
            e = edge_by_pair.get((min(i, j), max(i, j)))
            if e is not None:
                out.append(e)
    return out


def _pick_build(r1: float, r2: float):
    """Partitioned-both-sides default: build the smaller side.
    -> (build_rows, build_replicated, probe_rows)."""
    return (r1, False, r2) if r1 <= r2 else (r2, False, r1)


def _join_options(p1, a1: _Alt, p2, a2: _Alt, k1, k2, pairmap, nseg: int):
    """Yield (extra motion cost ns, output distribution prop,
    (build_rows, build_replicated, probe_rows)) for joining sides with
    properties p1/p2 over aligned key col-id lists k1/k2 — the
    cdbpath_motion_for_join decision menu. The build tuple lets the caller
    charge the hash build at its TRUE per-chip size: a broadcast/replicated
    build runs full-size on every chip (sort at ~40 ns/row/operand), which
    a bytes-only model undercharges by ~250x relative to its ICI cost."""
    r1, w1, r2, w2 = a1.rows, a1.width, a2.rows, a2.width
    if p1 == REPL:
        yield 0.0, (p2 if p2 != REPL else ()), (r1, True, r2)
        return
    if p2 == REPL:
        yield 0.0, p1, (r2, True, r1)
        return
    k1set, k2set = set(k1), set(k2)
    colocated = (p1 and len(p1) == len(p2)
                 and all(c in k1set for c in p1)
                 and tuple(pairmap.get(c) for c in p1) == tuple(p2))
    if colocated:
        yield 0.0, p1, _pick_build(r1, r2)
        return
    if p1 and all(c in k1set for c in p1):
        # move side 2 to match side 1's existing distribution
        yield (C.motion_cost("redistribute", r2, w2, nseg), p1,
               _pick_build(r1, r2))
    if p2 and all(c in k2set for c in p2):
        yield (C.motion_cost("redistribute", r1, w1, nseg), p2,
               _pick_build(r1, r2))
    yield ((C.motion_cost("redistribute", r1, w1, nseg)
            + C.motion_cost("redistribute", r2, w2, nseg)), tuple(k1),
           _pick_build(r1, r2))
    # broadcast side X => X is the (replicated, full-size) build side
    yield (C.motion_cost("broadcast", r2, w2, nseg), p1, (r2, True, r1))
    yield (C.motion_cost("broadcast", r1, w1, nseg), p2, (r1, True, r2))


def _expand(state: dict, s1: dict, s2: dict, mask1: int, xe, nseg: int) -> None:
    """Add all physical alternatives for joining group s1 x s2 across
    edges xe into ``state``, costed with the calibrated per-chip model."""
    pairs = []
    sel = 1.0
    for e in xe:
        sel *= e.sel
        if (1 << e.a) & mask1:
            pairs.extend(e.pairs)
        else:
            pairs.extend((b, a) for a, b in e.pairs)
    k1 = [a for a, _ in pairs]
    k2 = [b for _, b in pairs]
    pairmap = dict(pairs)
    nk = max(len(pairs), 1)

    for p1, a1 in s1.items():
        for p2, a2 in s2.items():
            rows = max(a1.rows * a2.rows * sel, 1.0)
            width = a1.width + a2.width
            # one HBM pass over both inputs + the output, per chip
            streams = (C.stream_cost(a1.rows, a1.width, nseg)
                       + C.stream_cost(a2.rows, a2.width, nseg)
                       + C.stream_cost(rows, width, nseg))
            for extra, prop, (brows, brepl, prows) in _join_options(
                    p1, a1, p2, a2, k1, k2, pairmap, nseg):
                local = (streams
                         + C.join_build_cost(brows, nk, nseg, replicated=brepl)
                         + C.join_probe_cost(prows, nk, nseg))
                cost = a1.cost + a2.cost + local + extra
                cur = state.get(prop)
                if cur is None or cost < cur.cost:
                    state[prop] = _Alt(cost, (a1.tree, a2.tree), rows, width,
                                       a1.leaves | a2.leaves)
