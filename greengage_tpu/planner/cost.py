"""TPU-oriented cost model — the libgpdbcost analog, radically smaller.

On TPU the dominant costs are HBM bytes touched and ICI bytes moved;
per-row CPU work (the reference's cpu_tuple_cost world) is nearly free
under vectorization. So costs are byte counts:

  redistribute(R)  ~ bytes(R)            (each row crosses ICI once)
  broadcast(R)     ~ bytes(R) * nseg     (all_gather replicates everywhere)
  local op(R)      ~ bytes(R)            (one HBM pass)

Row estimates come from storage manifests (exact for scans) and, after
ANALYZE, from column statistics (planner/stats.py — the
clauselist_selectivity / ORCA statistics-calculus analog): equality uses
MCV frequencies or 1/NDV, ranges interpolate [min, max], GROUP BY takes the
NDV product, joins divide by the larger key NDV. Without stats the round-1
constants remain as fallbacks. A mis-estimate here is expensive on TPU —
each capacity-overflow retry is a full XLA recompile — so stats pay for
themselves immediately.
"""

from __future__ import annotations

from greengage_tpu import expr as E

DEFAULT_FILTER_SELECTIVITY = 0.25
EQ_SELECTIVITY = 0.05
RANGE_SELECTIVITY = 0.33


def _col_and_lit(pred: E.Cmp):
    """-> (col_id, literal value, op oriented col-op-lit) or None."""
    left, right, op = pred.left, pred.right, pred.op
    flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
    if isinstance(left, E.Literal) and isinstance(right, E.ColRef):
        left, right, op = right, left, flip.get(op, op)
    if isinstance(left, E.ColRef) and isinstance(right, E.Literal) \
            and right.value is not None:
        try:
            return left.name, float(right.value), op
        except (TypeError, ValueError):
            return None
    return None


def _eq_sel(cs, v: float) -> float:
    for mval, frac in cs.mcv:
        if mval == v:
            return min(max(frac, 1e-6), 1.0)
    if cs.ndv > 0:
        return min((1.0 - cs.null_frac) / cs.ndv, 1.0)
    return EQ_SELECTIVITY


def _range_sel(cs, v: float, op: str) -> float:
    if cs.min is None or cs.max is None:
        return RANGE_SELECTIVITY
    lo, hi = cs.min, cs.max
    if hi <= lo:
        return 0.5
    frac = (v - lo) / (hi - lo)
    if op in ("<", "<="):
        s = frac
    else:
        s = 1.0 - frac
    return float(min(max(s, 0.0), 1.0)) * (1.0 - cs.null_frac)


def filter_selectivity(pred: E.Expr, lookup=None) -> float:
    """Estimated fraction of rows passing ``pred``. ``lookup`` maps a
    column id to its ColumnStats (or None) when the caller can resolve
    column origins; without it the constant fallbacks apply."""
    if isinstance(pred, E.Cmp):
        info = _col_and_lit(pred) if lookup is not None else None
        cs = lookup(info[0]) if info else None
        if cs is not None:
            _, v, op = info
            if op == "=":
                return _eq_sel(cs, v)
            if op == "<>":
                return max(1.0 - _eq_sel(cs, v) - cs.null_frac, 0.0)
            return _range_sel(cs, v, op)
        return EQ_SELECTIVITY if pred.op == "=" else RANGE_SELECTIVITY \
            if pred.op in ("<", "<=", ">", ">=") else DEFAULT_FILTER_SELECTIVITY
    if isinstance(pred, E.InList):
        cs = (lookup(pred.arg.name)
              if lookup is not None and isinstance(pred.arg, E.ColRef) else None)
        if cs is not None and cs.ndv > 0:
            return min(len(pred.values) * (1.0 - cs.null_frac) / cs.ndv, 1.0)
        return min(len(pred.values) * EQ_SELECTIVITY, 1.0)
    if isinstance(pred, E.IsNull):
        cs = (lookup(pred.arg.name)
              if lookup is not None and isinstance(pred.arg, E.ColRef) else None)
        if cs is not None:
            return (1.0 - cs.null_frac) if pred.negate else cs.null_frac
        return 0.9 if pred.negate else 0.1
    if isinstance(pred, E.Not):
        return max(1.0 - filter_selectivity(pred.arg, lookup), 1e-4)
    if isinstance(pred, E.BoolOp) and pred.op == "and":
        s = 1.0
        for a in pred.args:
            s *= filter_selectivity(a, lookup)
        return max(s, 1e-4)
    if isinstance(pred, E.BoolOp) and pred.op == "or":
        s = 0.0
        for a in pred.args:
            s += filter_selectivity(a, lookup)
        return min(s, 1.0)
    return DEFAULT_FILTER_SELECTIVITY


def row_width(cols) -> float:
    return 8.0 * max(len(cols), 1)


def est_groups(rows: float, ndvs: list[float] | None = None) -> float:
    """Group-count estimate. With per-key NDVs (ANALYZE ran): the NDV
    product capped at the row count — the standard independence bound.
    Without: the round-1 sqrt heuristic."""
    if ndvs:
        prod = 1.0
        for d in ndvs:
            prod *= max(d, 1.0)
            if prod >= rows:
                return max(rows, 1.0)
        return max(min(prod, rows), 1.0)
    import math

    return min(max(math.sqrt(max(rows, 1.0)) * 4, 16.0), 1 << 20)


def join_rows(left_rows: float, right_rows: float,
              key_ndvs: list[tuple[float, float]] | None) -> float | None:
    """Equi-join output estimate: |L||R| * prod 1/max(ndv_l, ndv_r).
    None when any key pair lacks stats (caller falls back)."""
    if not key_ndvs:
        return None
    sel = 1.0
    for nl, nr in key_ndvs:
        if nl <= 0 or nr <= 0:
            return None
        sel /= max(nl, nr)
    return max(left_rows * right_rows * sel, 1.0)


def motion_cost(kind: str, rows: float, width: float, nseg: int) -> float:
    if kind == "broadcast":
        return rows * width * nseg
    return rows * width
