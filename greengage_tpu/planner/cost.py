"""TPU-oriented cost model — the libgpdbcost analog, radically smaller,
CALIBRATED against measured v5e primitives (round-2 microbenchmarks at 6M
rows through the axon tunnel; see NOTES.md):

  random gather        64 ms / 6M rows (i32/f32)  ->  ~10.7 ns/row
  scatter(-add)       540 ms / 6M rows            ->  ~90   ns/row
  lax.sort          75-400 ms / 6M rows           ->  ~40   ns/row/operand
  HBM streaming pass  ~400 GB/s effective         ->  0.0025 ns/byte
  ICI all_to_all      ~50 GB/s per direction      ->  0.02  ns/byte
  device->host relay   65 ms/call + 28 MB/s       ->  ~36   ns/byte + fixed

Costs are estimated PER-CHIP WALL NANOSECONDS: global row counts divide by
nseg for partitioned work, but a broadcast build is full-size on every
chip — that asymmetry (sort-building a replicated table costs ~250x its
ICI transfer per row) is exactly what a bytes-only model got wrong, and
why the reference ships a calibrated CCostModelGPDB rather than raw I/O
counts.

Row estimates come from storage manifests (exact for scans) and, after
ANALYZE, from column statistics (planner/stats.py — the
clauselist_selectivity / ORCA statistics-calculus analog): equality uses
MCV frequencies or 1/NDV, ranges interpolate [min, max], GROUP BY takes the
NDV product, joins divide by the larger key NDV. Without stats the round-1
constants remain as fallbacks. A mis-estimate here is expensive on TPU —
each capacity-overflow retry is a full XLA recompile — so stats pay for
themselves immediately.
"""

from __future__ import annotations
import bisect
import math

from greengage_tpu import expr as E

DEFAULT_FILTER_SELECTIVITY = 0.25
EQ_SELECTIVITY = 0.05
RANGE_SELECTIVITY = 0.33

# measured v5e primitive costs (ns per row / byte); see module docstring.
# These are DEFAULTS: `gg checkperf --device --apply` re-measures them on
# the live chip and persists a <cluster>/calibration.json that
# set_calibration() loads at connect — on any other TPU generation the
# model tracks the hardware instead of silently reverting to folklore
# (the gpcheckperf + libgpdbcost calibration intent, gpMgmt/bin/gpcheckperf:1).
CALIBRATION_DEFAULTS = {
    "ns_gather_row": 10.7,
    "ns_scatter_row": 90.0,
    "ns_sort_row": 40.0,     # per sort operand (key or payload column)
    "ns_stream_byte": 0.0025,
    "ns_ici_byte": 0.02,
    "ns_host_byte": 36.0,    # axon device->host relay ~28 MB/s
    "ns_host_call": 65e6,    # fixed per device->host transfer
}


def set_calibration(values: dict | None) -> None:
    """Install measured primitive costs (keys of CALIBRATION_DEFAULTS;
    missing/invalid entries keep their defaults). None resets."""
    g = globals()
    for k, default in CALIBRATION_DEFAULTS.items():
        v = (values or {}).get(k, default)
        try:
            v = float(v)
        except (TypeError, ValueError):
            v = default
        g[k.upper()] = v if v > 0 else default


def current_calibration() -> dict:
    return {k: globals()[k.upper()] for k in CALIBRATION_DEFAULTS}


set_calibration(None)   # establish NS_GATHER_ROW .. NS_HOST_CALL globals

# Pipelined-motion overlap credit on the redistribute branch of
# motion_cost: with motion_pipeline on, the sub-exchange schedule
# (parallel/motion.py _exchange) and the host bucket pipeline
# (exec/motionpipe.py) hide part of each exchange behind neighboring
# compute, so the planner should not price a redistribute as if the
# device sat idle for the full transfer. Installed by the session from
# the motion_pipeline* GUCs (same process-global pattern as
# set_calibration — the SET broadcast keeps a multihost gang in
# lockstep). 1.0 = no credit (pipeline off / single bucket).
MOTION_PIPELINE_OVERLAP = 1.0


def set_motion_overlap(factor) -> None:
    """Install the redistribute overlap credit (0 < factor <= 1)."""
    global MOTION_PIPELINE_OVERLAP
    try:
        f = float(factor)
    except (TypeError, ValueError):
        f = 1.0
    MOTION_PIPELINE_OVERLAP = min(max(f, 0.25), 1.0)


def _value_of(e):
    """Estimation value of a comparison operand: a literal's value, or a
    hoisted parameter's est_value (sql/paramize.py — the value the
    statement that seeded the generic plan carried), unwrapping the
    binder's numeric-coercion Cast. None when unknown."""
    if isinstance(e, E.Literal):
        return e.value
    if isinstance(e, E.Param):
        return getattr(e, "_est_value", None)
    if isinstance(e, E.Cast) and isinstance(e.arg, E.Param):
        v = getattr(e.arg, "_est_value", None)
        if v is None:
            return None
        from greengage_tpu.sql.paramize import coerce_storage_value

        try:
            return coerce_storage_value(v, e.arg.type, e.type)
        except Exception:
            return None
    return None


def _col_and_lit(pred: E.Cmp):
    """-> (col_id, literal/param value, op oriented col-op-lit) or None."""
    left, right, op = pred.left, pred.right, pred.op
    flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
    if _value_of(left) is not None and isinstance(right, E.ColRef):
        left, right, op = right, left, flip.get(op, op)
    if isinstance(left, E.ColRef):
        v = _value_of(right)
        if v is not None:
            try:
                return left.name, float(v), op
            except (TypeError, ValueError):
                return None
    return None


def _eq_sel(cs, v: float) -> float:
    for mval, frac in cs.mcv:
        if mval == v:
            return min(max(frac, 1e-6), 1.0)
    if cs.ndv > 0:
        return min((1.0 - cs.null_frac) / cs.ndv, 1.0)
    return EQ_SELECTIVITY


def _hist_frac_below(hist: list, v: float) -> float:
    """Fraction of non-null values below ``v`` from an equi-depth histogram
    (planner/stats.py): whole buckets below v count 1/nbuckets each, the
    straddling bucket interpolates linearly within its boundaries — the
    CHistogram bucket-calculus / ineq_histogram_selectivity analog."""

    nb = len(hist) - 1
    if v <= hist[0]:
        return 0.0
    if v >= hist[-1]:
        return 1.0
    i = min(bisect.bisect_right(hist, v) - 1, nb - 1)
    lo, hi = hist[i], hist[i + 1]
    within = 0.5 if hi <= lo else (v - lo) / (hi - lo)
    return (i + min(max(within, 0.0), 1.0)) / nb


def _range_sel(cs, v: float, op: str) -> float:
    if len(cs.hist) >= 2:
        frac = _hist_frac_below(cs.hist, v)
        s = frac if op in ("<", "<=") else 1.0 - frac
        return float(min(max(s, 0.0), 1.0)) * (1.0 - cs.null_frac)
    if cs.min is None or cs.max is None:
        return RANGE_SELECTIVITY
    lo, hi = cs.min, cs.max
    if hi <= lo:
        return 0.5
    frac = (v - lo) / (hi - lo)
    if op in ("<", "<="):
        s = frac
    else:
        s = 1.0 - frac
    return float(min(max(s, 0.0), 1.0)) * (1.0 - cs.null_frac)


def filter_selectivity(pred: E.Expr, lookup=None) -> float:
    """Estimated fraction of rows passing ``pred``. ``lookup`` maps a
    column id to its ColumnStats (or None) when the caller can resolve
    column origins; without it the constant fallbacks apply."""
    if isinstance(pred, E.Cmp):
        info = _col_and_lit(pred) if lookup is not None else None
        cs = lookup(info[0]) if info else None
        if cs is not None:
            _, v, op = info
            if op == "=":
                return _eq_sel(cs, v)
            if op == "<>":
                return max(1.0 - _eq_sel(cs, v) - cs.null_frac, 0.0)
            return _range_sel(cs, v, op)
        return EQ_SELECTIVITY if pred.op == "=" else RANGE_SELECTIVITY \
            if pred.op in ("<", "<=", ">", ">=") else DEFAULT_FILTER_SELECTIVITY
    if isinstance(pred, E.InList):
        cs = (lookup(pred.arg.name)
              if lookup is not None and isinstance(pred.arg, E.ColRef) else None)
        if cs is not None and cs.ndv > 0:
            return min(len(pred.values) * (1.0 - cs.null_frac) / cs.ndv, 1.0)
        return min(len(pred.values) * EQ_SELECTIVITY, 1.0)
    if isinstance(pred, E.IsNull):
        cs = (lookup(pred.arg.name)
              if lookup is not None and isinstance(pred.arg, E.ColRef) else None)
        if cs is not None:
            return (1.0 - cs.null_frac) if pred.negate else cs.null_frac
        return 0.9 if pred.negate else 0.1
    if isinstance(pred, E.Not):
        return max(1.0 - filter_selectivity(pred.arg, lookup), 1e-4)
    if isinstance(pred, E.BoolOp) and pred.op == "and":
        s = 1.0
        for a in pred.args:
            s *= filter_selectivity(a, lookup)
        return max(s, 1e-4)
    if isinstance(pred, E.BoolOp) and pred.op == "or":
        s = 0.0
        for a in pred.args:
            s += filter_selectivity(a, lookup)
        return min(s, 1.0)
    return DEFAULT_FILTER_SELECTIVITY


def row_width(cols) -> float:
    return 8.0 * max(len(cols), 1)


def est_groups(rows: float, ndvs: list[float] | None = None) -> float:
    """Group-count estimate. With per-key NDVs (ANALYZE ran): the NDV
    product capped at the row count — the standard independence bound.
    Without: the round-1 sqrt heuristic."""
    if ndvs:
        prod = 1.0
        for d in ndvs:
            prod *= max(d, 1.0)
            if prod >= rows:
                return max(rows, 1.0)
        return max(min(prod, rows), 1.0)

    return min(max(math.sqrt(max(rows, 1.0)) * 4, 16.0), 1 << 20)


def join_rows(left_rows: float, right_rows: float,
              key_ndvs: list[tuple[float, float]] | None) -> float | None:
    """Equi-join output estimate: |L||R| * prod 1/max(ndv_l, ndv_r).
    None when any key pair lacks stats (caller falls back)."""
    if not key_ndvs:
        return None
    sel = 1.0
    for nl, nr in key_ndvs:
        if nl <= 0 or nr <= 0:
            return None
        sel /= max(nl, nr)
    return max(left_rows * right_rows * sel, 1.0)


def motion_cost(kind: str, rows: float, width: float, nseg: int) -> float:
    """Per-chip ns to move ``rows`` (GLOBAL count) of ``width`` bytes.
    Redistribute: each chip sends/receives ~rows/nseg. Broadcast: every
    chip receives (nseg-1)/nseg of the whole relation. Gather: the
    coordinator pulls everything through the device->host relay."""
    s = max(nseg, 1)
    if kind == "broadcast":
        return rows * width * NS_ICI_BYTE * (s - 1) / s
    if kind == "gather":
        return NS_HOST_CALL + rows * width * NS_HOST_BYTE
    return (rows / s) * width * NS_ICI_BYTE * MOTION_PIPELINE_OVERLAP


def stream_cost(rows: float, width: float, nseg: int = 1) -> float:
    """One HBM pass over a partitioned relation, per chip."""
    return (rows / max(nseg, 1)) * width * NS_STREAM_BYTE


def join_build_cost(rows: float, nkeys: int, nseg: int,
                    replicated: bool = False) -> float:
    """Sort-based hash-table build (ops/join.py): one multi-operand
    lax.sort + bucket scatter-add. A replicated (broadcast) build runs
    FULL-SIZE on every chip — no 1/nseg discount."""
    per_chip = rows if replicated else rows / max(nseg, 1)
    return per_chip * (NS_SORT_ROW * (nkeys + 2) + NS_SCATTER_ROW * 0.1)


def join_probe_cost(rows: float, nkeys: int, nseg: int) -> float:
    """Run-head walk: ~2 hops x one gather per key column per hop."""
    return (rows / max(nseg, 1)) * NS_GATHER_ROW * 2 * (nkeys + 1)


def agg_cost(rows: float, groups: float, nkeys: int, naggs: int,
             width: float, nseg: int) -> float:
    """One aggregation pass. Small group domains compile to the dense
    scatter-add path (stream-class: measured Q1 ~1.4 ns/row all-in);
    unbounded cardinality falls onto the sort-based path (a multi-operand
    sort of keys + payload dominates)."""
    s = max(nseg, 1)
    per_chip = rows / s
    if groups <= 4096:
        return per_chip * width * NS_STREAM_BYTE * max(naggs, 1)
    return per_chip * NS_SORT_ROW * (nkeys + max(naggs, 1))
