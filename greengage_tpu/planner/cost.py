"""TPU-oriented cost model — the libgpdbcost analog, radically smaller.

On TPU the dominant costs are HBM bytes touched and ICI bytes moved;
per-row CPU work (the reference's cpu_tuple_cost world) is nearly free
under vectorization. So costs are byte counts:

  redistribute(R)  ~ bytes(R)            (each row crosses ICI once)
  broadcast(R)     ~ bytes(R) * nseg     (all_gather replicates everywhere)
  local op(R)      ~ bytes(R)            (one HBM pass)

Row estimates come from storage manifests (exact for scans) and the usual
selectivity guesses elsewhere (clauselist_selectivity analog).
"""

from __future__ import annotations

from greengage_tpu import expr as E

DEFAULT_FILTER_SELECTIVITY = 0.25
EQ_SELECTIVITY = 0.05


def filter_selectivity(pred: E.Expr) -> float:
    if isinstance(pred, E.Cmp) and pred.op == "=":
        return EQ_SELECTIVITY
    if isinstance(pred, E.BoolOp) and pred.op == "and":
        s = 1.0
        for a in pred.args:
            s *= filter_selectivity(a)
        return max(s, 1e-4)
    if isinstance(pred, E.BoolOp) and pred.op == "or":
        s = 0.0
        for a in pred.args:
            s += filter_selectivity(a)
        return min(s, 1.0)
    return DEFAULT_FILTER_SELECTIVITY


def row_width(cols) -> float:
    return 8.0 * max(len(cols), 1)


def est_groups(rows: float) -> float:
    """Group-count guess without statistics: sqrt heuristic, capped."""
    import math

    return min(max(math.sqrt(max(rows, 1.0)) * 4, 16.0), 1 << 20)


def motion_cost(kind: str, rows: float, width: float, nseg: int) -> float:
    if kind == "broadcast":
        return rows * width * nseg
    return rows * width
