"""Logical/physical plan nodes.

The binder emits a motion-free tree; the planner (planner.py) annotates each
node with a Locus and inserts Motion nodes (the cdbparallelize/apply_motion
analog, src/backend/cdb/cdbllize.c:132, cdbmutate.c:396). The physical
compiler (exec/compile.py) walks the final tree.

Column identity: the binder assigns every column a unique id string; nodes
carry (output id -> type) schemas. TEXT columns additionally carry the
(table, column) of the dictionary that encodes them.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.planner.locus import Locus


@dataclass
class ColInfo:
    id: str
    type: T.SqlType
    name: str                      # user-facing output name
    dict_ref: tuple[str, str] | None = None   # (table, column) for TEXT
    hidden: bool = False           # ORDER BY pass-through, not in the result
    # raw-encoded TEXT (no dictionary): device carries a row surrogate,
    # strings decode at finalize via this (table, column)
    raw_ref: tuple[str, str] | None = None
    # string-function steps applied on the host after raw decode
    # (utils/strfuncs chain form)
    raw_chain: tuple | None = None


@dataclass
class Plan:
    locus: Locus | None = field(default=None, init=False)
    est_rows: float = field(default=0.0, init=False)

    @property
    def children(self) -> list["Plan"]:
        out = []
        for a in ("child", "left", "right"):
            c = getattr(self, a, None)
            if c is not None:
                out.append(c)
        return out

    # output schema
    def out_cols(self) -> list[ColInfo]:
        raise NotImplementedError


@dataclass
class ConstRel(Plan):
    """One-row constant relation — the FROM-less SELECT leaf (PG's
    degenerate RangeTblEntry-free Result plan). Live on segment 0 only,
    so the single logical row exists exactly once on the mesh."""

    def out_cols(self) -> list:
        return []


@dataclass
class Scan(Plan):
    table: str
    cols: list[ColInfo]            # id = unique, name = storage column name
    # direct dispatch (cdbtargeteddispatch.c analog): a distribution-key
    # equality pins every row of interest to ONE segment; only that
    # segment's storage is staged to device
    direct_seg: int | None = None
    # zone-map pruning (PartitionSelector/block-directory analog): pushed
    # conjuncts [(storage col, op, value)] let staging skip blocks whose
    # [min, max] cannot satisfy them
    prune_preds: tuple = ()
    # partitioned parent: child storage tables to stage (the full set from
    # the binder, statically pruned by the planner); None = unpartitioned.
    # parts_total remembers the pre-pruning count for EXPLAIN.
    parts: tuple | None = None
    parts_total: int = 0
    # secondary indexes whose column appears in the pushed conjuncts:
    # staging probes their block sidecars (EXPLAIN-visible access path)
    index_hits: tuple = ()
    # join-driven runtime partition elimination (PartitionSelector role):
    # (build table, build pushable preds, build join-key storage col) —
    # staging evaluates the build filter host-side and skips child
    # partitions no surviving key value can land in
    dyn_prune: tuple | None = None

    def out_cols(self):
        return self.cols


@dataclass
class Filter(Plan):
    child: Plan
    predicate: E.Expr

    def out_cols(self):
        return self.child.out_cols()


@dataclass
class Project(Plan):
    child: Plan
    exprs: list[tuple[ColInfo, E.Expr]]

    def out_cols(self):
        return [c for c, _ in self.exprs]


@dataclass
class Join(Plan):
    kind: str                      # inner | left | semi | anti | cross
    left: Plan
    right: Plan                    # build side
    left_keys: list[E.Expr]
    right_keys: list[E.Expr]
    residual: E.Expr | None = None
    multi: bool = False            # build side may have duplicate keys (CSR join)
    # NOT IN semantics (nodeSubplan's hashed-NOT-IN analog): result is empty
    # if the subquery produced any NULL key; NULL probe keys never qualify;
    # an empty subquery qualifies every probe row.
    null_aware: bool = False
    # direct-addressed build (ops/join.py build_direct): the single int
    # build key's stats-known dense domain [direct_lo, direct_lo+domain)
    direct_lo: int | None = None
    direct_domain: int | None = None

    def out_cols(self):
        if self.kind in ("semi", "anti"):
            return self.left.out_cols()
        return self.left.out_cols() + self.right.out_cols()


@dataclass
class Aggregate(Plan):
    child: Plan
    group_keys: list[tuple[ColInfo, E.Expr]]
    aggs: list[tuple[ColInfo, E.Agg]]
    phase: str = "single"          # single | partial | final
    partial_state: list | None = None  # set on final nodes by the planner

    def out_cols(self):
        return [c for c, _ in self.group_keys] + [c for c, _ in self.aggs]


@dataclass
class Sort(Plan):
    child: Plan
    keys: list[tuple[E.Expr, bool, bool | None]]   # expr, desc, nulls_first

    def out_cols(self):
        return self.child.out_cols()


@dataclass
class Limit(Plan):
    child: Plan
    limit: int | None
    offset: int = 0

    def out_cols(self):
        return self.child.out_cols()


@dataclass
class Window(Plan):
    """WindowAgg: per-partition functions over sorted rows (nodeWindowAgg.c).
    Each wfunc: (out ColInfo, func name, arg Expr|None, ordered, param).
    frame: None (default RANGE ..CURRENT ROW peers) or (preceding,
    following) ROWS offsets with None = unbounded."""

    child: Plan
    partition_keys: list[E.Expr]
    order_keys: list          # (expr, desc, nulls_first)
    wfuncs: list
    frame: tuple | None = None

    def out_cols(self):
        return self.child.out_cols() + [c for c, *_ in self.wfuncs]


@dataclass
class Union(Plan):
    inputs: list[Plan]             # branch outputs map positionally to cols
    cols: list[ColInfo]
    distinct: bool = False         # handled by an Aggregate the binder adds

    @property
    def children(self) -> list["Plan"]:
        return list(self.inputs)

    def out_cols(self):
        return self.cols


@dataclass
class PartialState(Plan):
    """Exposes a partial Aggregate's STATE columns (the @s/@c/@m naming the
    final phase consumes) as a schema — used by the spill executor to
    gather partial states to the host between passes (exec/spill.py)."""

    child: Plan
    cols: list[ColInfo]

    def out_cols(self):
        return self.cols


class MotionKind(enum.Enum):
    REDISTRIBUTE = "Redistribute"
    BROADCAST = "Broadcast"
    GATHER = "Gather"              # to the coordinator (Entry)


@dataclass
class Motion(Plan):
    kind: MotionKind
    child: Plan
    hash_exprs: list[E.Expr] = field(default_factory=list)  # REDISTRIBUTE only
    merge_keys: list | None = None  # GATHER: preserve this sort order
    # range repartition (REDISTRIBUTE only): rows route by sampled-splitter
    # ranges of ONE order-preserving encoded key instead of its hash, so
    # each segment owns a contiguous key range (equal keys co-locate) —
    # the gather-free ordered-global window path for keys that cannot pack
    # into the uint64 rank space (exec/compile.py _c_motion range branch).
    # {"expr", "desc", "nulls_first", "kind": "int"|"float"}
    range_spec: dict | None = None

    def out_cols(self):
        return self.child.out_cols()


def describe(plan: Plan, indent: int = 0, annot: dict | None = None) -> str:
    """EXPLAIN-style tree rendering (explain.c analog). ``annot`` maps
    id(plan) -> string appended per node (EXPLAIN ANALYZE row counts)."""
    pad = "  " * indent
    name = type(plan).__name__
    extra = ""
    if isinstance(plan, Scan):
        extra = f" {plan.table}"
        if plan.parts is not None:
            total = plan.parts_total or len(plan.parts)
            extra += f" (partitions: {len(plan.parts)}/{total})"
        if plan.direct_seg is not None:
            extra += f" (direct dispatch: seg {plan.direct_seg})"
        if plan.index_hits:
            extra += f" (index: {', '.join(plan.index_hits)})"
    elif isinstance(plan, Join):
        extra = f" {plan.kind}"
    elif isinstance(plan, Motion):
        extra = f" {plan.kind.value}"
        if plan.range_spec is not None:
            extra += " range"
        if plan.hash_exprs:
            extra += f" by ({', '.join(_expr_str(e) for e in plan.hash_exprs)})"
    elif isinstance(plan, Window):
        gm = getattr(plan, "global_mode", False)
        if gm:
            extra = f" global={'all' if gm is True else gm}"
    elif isinstance(plan, Aggregate):
        extra = f" {plan.phase} keys=({', '.join(c.name for c, _ in plan.group_keys)})"
    elif isinstance(plan, Limit):
        extra = f" {plan.limit}"
    elif isinstance(plan, Filter):
        extra = f" {_expr_str(plan.predicate)}"
    elif isinstance(plan, Project):
        shown = [f"{c.name}={_expr_str(e)}" for c, e in plan.exprs[:6]
                 if not isinstance(e, E.ColRef) or e.name != c.name]
        if shown:
            extra = f" [{', '.join(shown)}]"
    locus = f"  [{plan.locus.describe()}]" if plan.locus else ""
    rows = f" rows={int(plan.est_rows)}" if plan.est_rows else ""
    note = ""
    if annot and id(plan) in annot:
        note = f"  ({annot[id(plan)]})"
    lines = [f"{pad}{name}{extra}{locus}{rows}{note}"]
    for c in plan.children:
        lines.append(describe(c, indent + 1, annot))
    return "\n".join(lines)


def _expr_str(e: E.Expr) -> str:
    if isinstance(e, E.ColRef):
        return e.name
    if isinstance(e, E.Literal):
        return repr(e.value)
    if isinstance(e, E.BinOp) or isinstance(e, E.Cmp):
        return f"({_expr_str(e.left)} {e.op} {_expr_str(e.right)})"
    if isinstance(e, E.Func):
        return f"{e.name}({', '.join(_expr_str(a) for a in e.args)})"
    if isinstance(e, E.Cast):
        return _expr_str(e.arg)
    if isinstance(e, E.IsNull):
        neg = " not" if e.negate else ""
        return f"({_expr_str(e.arg)} is{neg} null)"
    if isinstance(e, E.BoolOp):
        return "(" + f" {e.op} ".join(_expr_str(a) for a in e.args) + ")"
    if isinstance(e, E.Not):
        return f"not {_expr_str(e.arg)}"
    return type(e).__name__
