"""Expression IR shared by the binder, planner, and device evaluator.

The reference evaluates expression trees per tuple (ExecQual/ExecProject,
src/backend/executor/execQual.c); we carry a small typed IR that the device
evaluator (ops/expr_eval.py) turns into whole-column JAX computations with
three-valued NULL logic.

String handling: TEXT columns are dictionary codes on device. The binder
lowers every string operation into either a code comparison (equality against
a literal present in the dictionary) or a ``Lut`` node — a host-computed
per-dictionary-entry table (bool for predicates like LIKE, int32 rank for
ORDER BY, int32 code translation for cross-table equality) gathered on
device. This keeps arbitrary string semantics off the TPU hot path at
O(dict_size) host cost per query.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from greengage_tpu import types as T


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class ColRef(Expr):
    name: str          # unique id assigned by the binder
    type: T.SqlType


@dataclass(frozen=True)
class Literal(Expr):
    value: object      # python scalar in storage representation (DECIMAL: scaled int)
    type: T.SqlType

    @staticmethod
    def null(type_: T.SqlType) -> "Literal":
        return Literal(None, type_)


@dataclass(frozen=True)
class Param(Expr):
    """A hoisted literal — a runtime parameter of the compiled program.

    The paramize pass (sql/paramize.py) replaces plan-safe literals with
    Params so one XLA executable serves every value of a query shape; the
    executor feeds each slot's value as a traced scalar input. Params are
    never NULL and never TEXT (string literals stay pinned: dictionary
    codes and LIKE lowering are bind-time value rewrites)."""

    slot: int
    type: T.SqlType


@dataclass(frozen=True)
class BinOp(Expr):
    op: str            # + - * / %
    left: Expr
    right: Expr
    type: T.SqlType


@dataclass(frozen=True)
class Cmp(Expr):
    op: str            # = <> < <= > >=
    left: Expr
    right: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class BoolOp(Expr):
    op: str            # and | or  (Kleene 3VL)
    args: tuple[Expr, ...]
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Not(Expr):
    arg: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr
    negate: bool = False
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Case(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    else_: Expr | None
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Lut(Expr):
    """table[codes] gather; table is a host numpy array of len(dictionary).

    An out-of-dictionary sentinel row is appended by the builder so code -1
    (absent literal) can be represented as index len(table)-1.
    """

    arg: Expr
    table_id: str       # key into the plan's constant pool
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class RawChain(Expr):
    """String-function chain over a raw-encoded TEXT column.

    The device carries the column's row surrogate unchanged; the chain is
    applied on the host — at predicate staging (table_store.eval_host_pred)
    or at result decode (executor finalize). chain = ((name, *literal_args),
    ...) in application order; see utils/strfuncs.py for semantics.
    """

    arg: Expr           # base-table ColRef of the raw column
    chain: tuple = ()
    type: T.SqlType = T.TEXT


@dataclass(frozen=True)
class InList(Expr):
    arg: Expr
    values: tuple       # storage-representation scalars
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Func(Expr):
    """Scalar function call evaluated on device (registry in expr_eval)."""

    name: str           # extract_year | extract_month | extract_day | abs | ...
    args: tuple[Expr, ...] = ()
    type: T.SqlType = T.INT32


@dataclass(frozen=True)
class RawLike(Expr):
    """General LIKE over a raw TEXT column, evaluated ON DEVICE from the
    staged wide byte window (@rw word lanes + @rl length): the pattern's
    literal parts (split on %) match greedily left-to-right over an
    unpacked [rows, W] byte matrix — varlena.c text_like vectorized
    (VERDICT r4 #7). The planner only emits this when every committed row
    fits the window, so device results are exact."""

    words: tuple          # ColRefs of @rw:<col>:<w> int64 lanes, in order
    length: "Expr"        # ColRef of @rl:<col>
    parts: tuple          # literal parts as bytes, in pattern order
    anchored_start: bool
    anchored_end: bool
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Agg(Expr):
    func: str           # count | count_star | sum | min | max | avg
    arg: Expr | None
    distinct: bool
    type: T.SqlType


def agg_result_type(func: str, arg_type: T.SqlType | None) -> T.SqlType:
    if func in ("count", "count_star"):
        return T.INT64
    if func == "avg":
        # PG returns numeric for int/decimal avg; we use float64 (documented
        # deviation: avg is inexact, sums remain exact)
        return T.FLOAT64
    if func in ("min", "max"):
        return arg_type
    if func == "sum":
        if arg_type.kind is T.Kind.DECIMAL:
            return arg_type
        if arg_type.is_integer:
            return T.INT64
        return T.FLOAT64
    raise ValueError(f"unknown aggregate {func}")


def walk(e: Expr):
    yield e
    for f in (
        getattr(e, "left", None), getattr(e, "right", None), getattr(e, "arg", None),
        getattr(e, "else_", None), getattr(e, "length", None),
    ):
        if isinstance(f, Expr):
            yield from walk(f)
    for a in getattr(e, "args", ()) or ():
        yield from walk(a)
    for a in getattr(e, "words", ()) or ():
        yield from walk(a)
    for c, v in getattr(e, "whens", ()):
        yield from walk(c)
        yield from walk(v)


def columns_used(e: Expr) -> set[str]:
    return {n.name for n in walk(e) if isinstance(n, ColRef)}
