"""Expression IR shared by the binder, planner, and device evaluator.

The reference evaluates expression trees per tuple (ExecQual/ExecProject,
src/backend/executor/execQual.c); we carry a small typed IR that the device
evaluator (ops/expr_eval.py) turns into whole-column JAX computations with
three-valued NULL logic.

String handling: TEXT columns are dictionary codes on device. The binder
lowers every string operation into either a code comparison (equality against
a literal present in the dictionary) or a ``Lut`` node — a host-computed
per-dictionary-entry table (bool for predicates like LIKE, int32 rank for
ORDER BY, int32 code translation for cross-table equality) gathered on
device. This keeps arbitrary string semantics off the TPU hot path at
O(dict_size) host cost per query.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from greengage_tpu import types as T


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class ColRef(Expr):
    name: str          # unique id assigned by the binder
    type: T.SqlType


@dataclass(frozen=True)
class Literal(Expr):
    value: object      # python scalar in storage representation (DECIMAL: scaled int)
    type: T.SqlType

    @staticmethod
    def null(type_: T.SqlType) -> "Literal":
        return Literal(None, type_)


@dataclass(frozen=True)
class Param(Expr):
    """A hoisted literal — a runtime parameter of the compiled program.

    The paramize pass (sql/paramize.py) replaces plan-safe literals with
    Params so one XLA executable serves every value of a query shape; the
    executor feeds each slot's value as a traced scalar input. Params are
    never NULL and never TEXT (string literals stay pinned: dictionary
    codes and LIKE lowering are bind-time value rewrites)."""

    slot: int
    type: T.SqlType


@dataclass(frozen=True)
class BinOp(Expr):
    op: str            # + - * / %
    left: Expr
    right: Expr
    type: T.SqlType


@dataclass(frozen=True)
class Cmp(Expr):
    op: str            # = <> < <= > >=
    left: Expr
    right: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class BoolOp(Expr):
    op: str            # and | or  (Kleene 3VL)
    args: tuple[Expr, ...]
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Not(Expr):
    arg: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr
    negate: bool = False
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Case(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    else_: Expr | None
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Lut(Expr):
    """table[codes] gather; table is a host numpy array of len(dictionary).

    An out-of-dictionary sentinel row is appended by the builder so code -1
    (absent literal) can be represented as index len(table)-1.
    """

    arg: Expr
    table_id: str       # key into the plan's constant pool
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class RawChain(Expr):
    """String-function chain over a raw-encoded TEXT column.

    The device carries the column's row surrogate unchanged; the chain is
    applied on the host — at predicate staging (table_store.eval_host_pred)
    or at result decode (executor finalize). chain = ((name, *literal_args),
    ...) in application order; see utils/strfuncs.py for semantics.
    """

    arg: Expr           # base-table ColRef of the raw column
    chain: tuple = ()
    type: T.SqlType = T.TEXT


@dataclass(frozen=True)
class InList(Expr):
    arg: Expr
    values: tuple       # storage-representation scalars
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Func(Expr):
    """Scalar function call evaluated on device.

    Resolution order in the evaluator: the device scalar library
    (ops/scalar.py — typed registry with per-function NULL semantics),
    then the extension UDF registry (extensions.py). ``params`` carries
    bind-time static arguments the device implementation needs baked into
    the traced program (DECIMAL scales, date_trunc field, interval
    months) — never row data."""

    name: str           # extract_year | date_trunc | coalesce | round_dec ...
    args: tuple[Expr, ...] = ()
    type: T.SqlType = T.INT32
    params: tuple = ()


@dataclass(frozen=True)
class RawLike(Expr):
    """General LIKE over a raw TEXT column, evaluated ON DEVICE from the
    staged wide byte window (@rw word lanes + @rl length): the pattern's
    literal parts (split on %) match greedily left-to-right over an
    unpacked [rows, W] byte matrix — varlena.c text_like vectorized
    (VERDICT r4 #7). The planner only emits this when every committed row
    fits the window, so device results are exact."""

    words: tuple          # ColRefs of @rw:<col>:<w> int64 lanes, in order
    length: "Expr"        # ColRef of @rl:<col>
    parts: tuple          # literal parts as bytes, in pattern order
    anchored_start: bool
    anchored_end: bool
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class RawStrOp(Expr):
    """Scalar string-function chain over a raw TEXT column, evaluated ON
    DEVICE from the staged wide byte window (@rw word lanes + @rl length)
    — the byte-op half of the scalar data-path fusion (ops/scalar.py;
    docs/PERF.md "Scalar data-path fusion"). The chain's steps never move
    bytes: they narrow a per-row (start, length) view over the unpacked
    [rows, W] byte matrix (substr/left/right/trim) or transform the matrix
    elementwise (upper/lower), so the whole expression is VPU
    elementwise/reduce work with no gather.

    Terminal op:
      - out="length": the view's length (INT32) — usable anywhere a
        device int is (projections, aggregates, predicates);
      - out="cmp": equality of the view against ``literal`` (BOOL);
      - out="like": RawLike's greedy %-part matching constrained to the
        view (BOOL).

    The binder only emits this when every committed row fits the staged
    window and the column is pure ASCII where the chain's semantics
    require it (upper/lower/substr/length count characters, the window
    counts bytes)."""

    words: tuple          # ColRefs of @rw:<col>:<w> int64 lanes, in order
    length: "Expr"        # ColRef of @rl:<col>
    steps: tuple = ()     # ((name, *literal args), ...) in application order
    out: str = "cmp"      # cmp | like | length
    literal: bytes = b""  # out="cmp": utf-8 bytes of the compared literal
    parts: tuple = ()     # out="like": literal parts as bytes
    anchored_start: bool = True
    anchored_end: bool = True
    type: T.SqlType = T.BOOL


@dataclass(frozen=True)
class Agg(Expr):
    func: str           # count | count_star | sum | min | max | avg
    arg: Expr | None
    distinct: bool
    type: T.SqlType


def agg_result_type(func: str, arg_type: T.SqlType | None) -> T.SqlType:
    if func in ("count", "count_star"):
        return T.INT64
    if func == "avg":
        # PG returns numeric for int/decimal avg; we use float64 (documented
        # deviation: avg is inexact, sums remain exact)
        return T.FLOAT64
    if func in ("min", "max"):
        return arg_type
    if func == "sum":
        if arg_type.kind is T.Kind.DECIMAL:
            return arg_type
        if arg_type.is_integer:
            return T.INT64
        return T.FLOAT64
    raise ValueError(f"unknown aggregate {func}")


def walk(e: Expr):
    yield e
    for f in (
        getattr(e, "left", None), getattr(e, "right", None), getattr(e, "arg", None),
        getattr(e, "else_", None), getattr(e, "length", None),
    ):
        if isinstance(f, Expr):
            yield from walk(f)
    for a in getattr(e, "args", ()) or ():
        yield from walk(a)
    for a in getattr(e, "words", ()) or ():
        yield from walk(a)
    for c, v in getattr(e, "whens", ()):
        yield from walk(c)
        yield from walk(v)


def columns_used(e: Expr) -> set[str]:
    return {n.name for n in walk(e) if isinstance(n, ColRef)}
